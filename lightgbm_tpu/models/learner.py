"""TPU tree learner: wraps the device grower, assembles host Tree models.

The analog of the reference's learner factory slot (reference
src/treelearner/tree_learner.cpp:13-36): the serial learner here IS the
device learner (device offload is the default, like `device_type=gpu`
composing with the serial learner, gpu_tree_learner.cpp:739-750).  Parallel
variants wrap the same grower with mesh shardings (lightgbm_tpu.parallel).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Config
from ..io.bin_mapper import MissingType
from ..io.dataset import TrainingData
from ..ops.grower import (GrowerParams, canonical_params, mode_flags_np,
                          pad_rows, pool_dtype, resolve_split_batch)
from ..ops.histogram import hashed_uniform, key_words
from ..parallel.mesh import put_global, put_local
from ..parallel.strategies import (bins_sharding, make_strategy_grower,
                                   pool_partition_spec,
                                   resolve_tree_learner, rows_sharding)
from ..utils import timer
from ..utils.log import Log
from .tree import Tree


def _to_bitset(values) -> list:
    """Int values -> uint32 bitset words (reference Common::ConstructBitset,
    include/LightGBM/utils/common.h)."""
    vals = [int(v) for v in values if int(v) >= 0]
    if not vals:
        return [0]
    words = [0] * (max(vals) // 32 + 1)
    for v in vals:
        words[v // 32] |= 1 << (v % 32)
    return words


class TPUTreeLearner:
    # True on StreamedTreeLearner (ops/stream.py): the binned matrix
    # stays HOST-resident and serial placement routes through the
    # _place_serial_bins hook instead of a device transpose/pack
    stream_layout = False

    def __init__(self, config: Config, train_data: TrainingData):
        self.config = config
        self.td = train_data
        # persistent XLA compilation cache (tpu_compile_cache_dir): wire
        # it up at first device use so repeat runs of the same shapes
        # skip the cold compile tail; off by default
        cache_dir = str(config.tpu_compile_cache_dir or "")
        if cache_dir:
            from ..utils.backend import enable_compilation_cache

            enable_compilation_cache(cache_dir, min_compile_time_secs=0.0)
        n = train_data.num_data
        self.num_features = train_data.num_features
        if self.num_features == 0:
            raise ValueError("no usable features in training data")

        meta_np = dict(train_data.feature_arrays())
        # CEGB feature-acquisition penalties, mapped onto used features
        # (reference config.h cegb_penalty_feature_coupled/_lazy)
        def _per_feature(raw):
            vals = np.zeros(train_data.num_features, np.float32)
            for j, col in enumerate(train_data.used_feature_idx):
                if col < len(raw):
                    vals[j] = raw[col]
            return vals

        coupled_raw = [float(v) for v in config.cegb_penalty_feature_coupled]
        lazy_raw = [float(v) for v in config.cegb_penalty_feature_lazy]
        meta_np["cegb_coupled"] = _per_feature(coupled_raw)
        meta_np["cegb_lazy"] = _per_feature(lazy_raw)
        # all-zero penalty lists are no-ops in the reference (IsEnable,
        # cost_effective_gradient_boosting.hpp:25-31 checks emptiness, but
        # zeros charge nothing) — don't pay for the machinery
        has_cegb_lazy = any(v != 0.0 for v in lazy_raw)
        has_cegb = (any(v != 0.0 for v in coupled_raw) or has_cegb_lazy
                    or float(config.cegb_penalty_split) != 0.0)
        self.meta_np = meta_np
        forced = self._parse_forced_splits(config, train_data)
        B = int(meta_np["num_bin"].max())
        self.num_bins = B

        # ---- strategy resolution (the factory slot,
        # reference tree_learner.cpp:13-36 + CheckParamConflict which
        # degrades parallel learners to serial when num_machines==1) ----
        strategy = resolve_tree_learner(config.tree_learner)
        n_shards = int(config.num_machines)
        if strategy != "serial":
            if str(config.machines):
                # multi-host: machine list -> jax.distributed global mesh
                # (the Linkers-socket rendezvous role,
                # linkers_socket.cpp:165-220); single-process runs skip it
                from ..parallel.mesh import init_multihost

                init_multihost(str(config.machines),
                               int(config.local_listen_port), n_shards)
            ndev = len(jax.devices())
            if n_shards <= 1:
                Log.warning(f"tree_learner={strategy} needs num_machines>1; "
                            "falling back to serial")
                strategy = "serial"
            elif n_shards > ndev:
                raise ValueError(
                    f"num_machines={n_shards} exceeds the {ndev} available "
                    f"devices ({jax.devices()[0].platform})")
        self.n_shards = n_shards if strategy != "serial" else 1
        # hosts axis of the (hosts, data, feature) topology — the
        # process/DCN tier.  tpu_topology_hosts>0 pins it (simulated
        # multi-host grids on one process); 0 follows the live process
        # count.  Live multi-process runs must agree with reality: the
        # put_local/put_global placement contracts key on it.
        from ..parallel.topology import resolve_hosts

        self.hosts = (resolve_hosts(int(config.tpu_topology_hosts))
                      if strategy != "serial" else 1)
        if (strategy != "serial" and jax.process_count() > 1
                and self.hosts != jax.process_count()):
            raise ValueError(
                f"tpu_topology_hosts={self.hosts} disagrees with the live "
                f"process count {jax.process_count()}; leave it 0 (auto) "
                "on real multi-host meshes")
        # 2-D factorization: rows on (hosts, data) x features on
        # 'feature' (reference parallel_tree_learner.h:25-187 template
        # nesting)
        if strategy == "data_feature":
            fs = int(config.tpu_feature_shards)
            if fs <= 0:
                # auto: 2 feature shards when the device count factors,
                # else degrade to a (n, 1) mesh — 1-sized axes are valid
                # (the collectives become no-ops) so odd/prime counts
                # still train instead of crashing on a value the user
                # never set
                fs = 2 if (self.n_shards % 2 == 0 and self.n_shards > 2) \
                    else 1
            if self.n_shards % fs != 0:
                raise ValueError(
                    f"tpu_feature_shards={fs} must divide "
                    f"num_machines={self.n_shards}")
            self.f_shards = fs
            self.d_shards = self.n_shards // fs
        elif strategy == "feature":
            if self.hosts > 1:
                # feature sharding across hosts: no host holds every row
                # once the hosts axis is real, so rows ride the hosts
                # axis (one row shard per host) and each host's devices
                # shard the features — the data_feature composition with
                # d_shards == hosts.  Split decisions are gain-identical
                # to 1-host feature sharding: histograms psum exactly
                # over the row axes and the best-split sync shares the
                # deterministic tie-break.
                if self.n_shards % self.hosts != 0:
                    raise ValueError(
                        f"num_machines={self.n_shards} must split evenly "
                        f"across {self.hosts} hosts for tree_learner="
                        "feature")
                strategy = "data_feature"
                self.f_shards = self.n_shards // self.hosts
                self.d_shards = self.hosts
            else:
                self.f_shards, self.d_shards = self.n_shards, 1
        else:
            self.f_shards, self.d_shards = 1, self.n_shards
        self.strategy = strategy

        # ---- pre-partitioned training rows (reference loader
        # pre_partition, dataset_loader.cpp row distribution): each
        # PROCESS holds only its local row shard, so the row geometry
        # and device placement below become process-local and metrics
        # reduce globally (parallel/metric_sync).  DERIVED, not gated on
        # strategy: every parallel learner rides the same (hosts, data,
        # feature) mesh, so the old feature/EFB refusals are gone.
        self._partitioned = (bool(config.pre_partition)
                             and strategy != "serial"
                             and jax.process_count() > 1)
        if self._partitioned:
            if self.n_shards != len(jax.devices()):
                raise ValueError(
                    "pre_partition requires num_machines == the total "
                    f"device count ({len(jax.devices())}); got "
                    f"{self.n_shards}")
            if self.d_shards % jax.process_count() != 0:
                raise ValueError("row shards must split evenly across "
                                 "processes for pre_partition")

        for key, allowed in (("tpu_partition_impl", ("select", "vselect",
                                                     "gather", "kernel")),
                             ("tpu_hist_impl", ("auto", "xla", "pallas",
                                                "pallas2", "fused")),
                             ("tpu_hist_precision", ("hilo", "bf16", "f32",
                                                     "f64", "int8", "int16")),
                             ("tpu_quant_round", ("stochastic", "nearest")),
                             ("tpu_hist_agg", ("auto", "psum", "scatter")),
                             ("tpu_bucket_policy", ("fine", "wide")),
                             ("tpu_autotune", ("off", "load", "tune"))):
            if str(getattr(config, key)) not in allowed:
                raise ValueError(f"{key}={getattr(config, key)!r}; "
                                 f"expected one of {allowed}")
        self.hist_agg = self._resolve_hist_agg(config, strategy,
                                               self.d_shards)

        precision = self._resolve_precision(config)
        quantized = precision in ("int8", "int16")

        # feature axis padded to a multiple of the shard count; padding
        # features are trivial (num_bin=1) and can never split
        self.f_pad = self.num_features
        if self.f_shards > 1:
            self.f_pad = (-(-self.num_features // self.f_shards)
                          * self.f_shards)

        # layout phase timer (bench.py splits ingest into sketch / bin /
        # layout): everything from EFB planning to the placed device
        # arrays below counts as layout
        _t_layout = time.perf_counter()

        # ---- EFB bundling (reference FindGroups/FastFeatureBundling,
        # dataset.cpp:91-263): sparse zero-default features share columns,
        # shrinking the histogram matrix's feature axis ----
        plan = None
        if (bool(config.enable_bundle) and strategy not in ("serial", "data")
                and self.num_features > 1):
            # voting/feature learners train unbundled (the grower's
            # bundle expansion composes with serial/data only) — say so
            # instead of silently dropping the requested EFB
            Log.info(f"EFB bundling is inactive under tree_learner="
                     f"{strategy}; training on plain columns")
        if (bool(config.enable_bundle) and strategy in ("serial", "data")
                and not forced and self.num_features > 1
                and not self.stream_layout):
            from ..io.bundling import (EFB_SAMPLE_ROWS, find_bundles,
                                       find_bundles_multihost)

            zero_frac = train_data.column_zero_fraction()
            if self._partitioned:
                # every rank must greedy-group the SAME plan or the
                # global arrays' num_columns/meta diverge; all plan-
                # determining statistics reduce inside the helper
                cand_plan = find_bundles_multihost(
                    train_data.bins, meta_np["num_bin"], zero_frac, n,
                    float(config.sparse_threshold),
                    float(config.max_conflict_rate), B)
            else:
                # the greedy only ever reads the strided row sample;
                # hand it exactly that sample (a bounded device fetch
                # when the matrix is device-resident) instead of the
                # full host matrix — identical rows, identical plan
                cand_plan = find_bundles(
                    train_data.strided_row_sample(EFB_SAMPLE_ROWS),
                    meta_np["num_bin"],
                    zero_frac >= float(config.sparse_threshold),
                    float(config.max_conflict_rate), B,
                    sample_rows=EFB_SAMPLE_ROWS)
            if not cand_plan.is_trivial:
                plan = cand_plan
                B = max(B, int(plan.num_bin.max()))
                self.num_bins = B
                Log.info(
                    f"EFB: bundled {self.num_features} features into "
                    f"{plan.num_columns} columns")
        self.bundle_plan = plan

        if plan is not None:
            from ..io.bundling import apply_bundles

            cols_src = apply_bundles(train_data.bins, plan)
            dev_src = None
            meta_np["bundle_idx"] = plan.bundle_idx.astype(np.int32)
            meta_np["bin_offset"] = plan.bin_offset.astype(np.int32)
            meta_np["needs_fix"] = plan.needs_fix.astype(np.int32)
            self.num_columns = cols_src.shape[1]
        else:
            # device-resident ingest keeps the host matrix lazy: the
            # plain-column layout below can transpose on device, so
            # cols_src stays unmaterialized until a host-only path
            # (sparse COO packing, parallel placement) asks for it
            dev_src = train_data.device_ingest_bins()
            cols_src = None if dev_src is not None else train_data.bins
            F_ = self.num_features
            meta_np["bundle_idx"] = np.arange(F_, dtype=np.int32)
            meta_np["bin_offset"] = np.zeros(F_, np.int32)
            meta_np["needs_fix"] = np.zeros(F_, np.int32)
            self.num_columns = F_
        self.g_pad = (self.f_pad if self.f_shards > 1 else self.num_columns)

        # ---- sparse train-time storage (reference OrderedSparseBin,
        # src/io/ordered_sparse_bin.hpp / sparse_bin.hpp:73): features
        # whose nonzero-bin fraction is <= tpu_sparse_threshold keep only
        # their O(nnz) (row, bin) pairs; the dense [Gd, n] matrix holds
        # the rest.  Wide very-sparse data (Bosch-shaped 1M x 968 @ ~2%)
        # stops paying dense HBM for rows sitting at the zero bin. ----
        self._sparse_mask = None
        sth = float(config.tpu_sparse_threshold)
        if sth > 0.0:
            if quantized:
                # the sparse zero-bin reconstruction mixes histogram rows
                # with scalar leaf totals; keeping that exact in the
                # integer domain is future work — reject loudly
                raise ValueError(
                    "tpu_sparse_threshold does not compose with quantized "
                    "histogram precisions (tpu_hist_precision=int8|int16)")
            if bool(config.enable_bundle):
                # deterministic gate on the FLAG, not on whether a plan
                # happened to form for this data — the error must not
                # depend on bundle-ability
                raise ValueError(
                    "tpu_sparse_threshold requires enable_bundle=false "
                    "(EFB already re-columns sparse features; pick one)")
            if strategy not in ("serial", "data", "voting"):
                raise NotImplementedError(
                    "tpu_sparse_threshold requires tree_learner=serial, "
                    "data, or voting (feature sharding replicates rows)")
            if forced:
                raise ValueError("tpu_sparse_threshold does not compose "
                                 "with forced splits")
            zb_f = meta_np["default_bin"]
            # one vectorized (bins != zero_bin).sum(axis=0) pass — the
            # sparse gate implies enable_bundle=false, so the columns
            # are the plain training bins; the helper row-chunks the
            # boolean temporary (Bosch scale) and reduces on device
            # when the matrix is device-resident
            nz_counts = train_data.column_nonzero_counts(zb_f)
            denom = n
            if self._partitioned:
                # every rank must agree on WHICH features are sparse, or
                # Gs/perm diverge and the global tables are inconsistent
                # — decide from the GLOBAL nonzero fractions
                from ..parallel.topology import host_allgather

                g = host_allgather(
                    np.concatenate([nz_counts, [n]]).astype(np.int32),
                    name="sparse_global_fractions")
                tot = g.sum(axis=0)
                nz_counts, denom = tot[:-1], int(tot[-1])
            nz_frac = nz_counts / max(denom, 1)
            sp_mask = nz_frac <= sth
            if sp_mask.all():
                # the dense kernel needs a nonempty matrix; keep the
                # densest feature dense
                sp_mask[int(np.argmax(nz_frac))] = False
            if sp_mask.any():
                self._sparse_mask = sp_mask

        # impl/block resolution happens HERE, once, with the final
        # histogram shape: bundling above only needs the host bin matrix,
        # while the padded row count below depends on the resolved block.
        # (The perfeature kernel chunks the feature axis itself, so the
        # VMEM fit depends only on the bin count, not the feature width.)
        # persisted autotune profile (utils/autotune.py): measured winners
        # for this (platform, device count, shape bucket) override the
        # "auto" heuristics below; a stale profile (other topology) raises
        # AutotuneStaleProfile here rather than training on wrong winners
        self._autotune_entry = None
        if str(config.tpu_autotune) != "off":
            from ..utils.autotune import resolve_autotune

            self._autotune_entry = resolve_autotune(
                config, n, self.num_features, B, precision)
        hist_impl, block = self._resolve_hist_impl(
            config, B, precision, tuned=self._autotune_entry)
        if hist_impl in ("pallas2", "fused"):
            # the perfeature kernel chunks its feature grid in
            # sublane-aligned (multiple-of-32) divisors (ops/histogram.py
            # _hist_pallas); pad the histogram column axis so every width
            # admits aligned chunks.  Padding columns hold constant bin 0
            # (num_bin=1 features) and can never split.  Feature-parallel
            # pads to 32 * n_shards so each shard's slice stays aligned
            if self.f_shards > 1:
                a = 32 * self.f_shards
                self.f_pad = -(-self.f_pad // a) * a
                self.g_pad = self.f_pad
            elif plan is None:
                self.f_pad = -(-self.f_pad // 32) * 32
                self.g_pad = self.f_pad
            else:
                self.g_pad = -(-self.g_pad // 32) * 32
        # ---- shape bucketing (compile-cache policy): quantize the padded
        # axes so at most `tpu_shape_buckets` distinct shapes exist per
        # power-of-2 octave — a new dataset of similar size then hits the
        # persistent compilation cache instead of paying the 70-150 s
        # cold remote compile (SURVEY §7 "dispatch overhead is the #1
        # wall-clock risk").  Worst-case pad waste is 2/buckets (~6% at
        # the default 32); 0 disables (exact block-multiple padding,
        # maximum throughput — bench.py pins this).
        buckets = int(config.tpu_shape_buckets)

        def bucket_up(count: int, quantum: int) -> int:
            padded = -(-count // quantum) * quantum
            if buckets <= 0:
                return padded
            q = quantum
            while q * buckets < padded:
                q *= 2
            return -(-count // q) * q

        def bucket_rows(count: int) -> int:
            # supra-block: quantize the BLOCK COUNT (pad_rows clamps the
            # block to the row count, so derive the effective block the
            # same way).  Sub-block (count < tpu_block_rows, the common
            # case on TPU where the resolved block is 8-16k): quantize
            # the row count itself from the 128-lane tile upward, capped
            # at one block — without this, every sub-block n is its own
            # XLA program
            eff = min(block, max(count, 1))
            base = pad_rows(count, block)
            if buckets <= 0:
                return base
            if base >= block:
                return bucket_up(base // eff, 1) * eff
            return min(bucket_up(count, 128), block)

        if self._partitioned:
            # rows per shard must be UNIFORM across the whole mesh: size
            # from the largest process's share (short ranks pad with
            # masked rows); n here is only THIS process's row count
            from ..parallel.topology import host_allgather

            shards_local = self.d_shards // jax.process_count()
            ns = host_allgather(np.asarray([n], np.int32),
                                name="shard_rows_sync")
            max_shard_rows = -(-int(ns.max()) // shards_local)
            self.n_pad = bucket_rows(max_shard_rows) * self.d_shards
            self._local_width = (self.n_pad // self.d_shards) * shards_local
        elif self.d_shards > 1:
            # every shard holds an equal, whole number of histogram blocks
            self.n_pad = bucket_rows(
                (n + self.d_shards - 1) // self.d_shards) * self.d_shards
        else:
            self.n_pad = bucket_rows(n)
        # feature axis: bucket above the alignment the padding code above
        # already established (32-multiples for pallas2, shard-count
        # multiples for feature sharding); padding features are trivial
        # (num_bin=1) and can never split
        if buckets > 0:
            if hist_impl == "pallas2":
                align = 32 * self.f_shards if self.f_shards > 1 else 32
            else:
                align = self.f_shards if self.f_shards > 1 else 8
            if self.g_pad == self.f_pad:
                self.f_pad = bucket_up(self.f_pad, align)
                self.g_pad = self.f_pad
            else:
                # EFB keeps g_pad (bundle columns) separate from f_pad
                self.g_pad = bucket_up(self.g_pad, align)

        # ---- scatter-aggregation alignment (tpu_hist_agg=scatter): the
        # reduce-scatter hands shard d a contiguous 1/P slice of the
        # histogram column axis, so that axis must divide by the data-
        # shard count — on top of whatever alignment feature sharding /
        # the pallas2 kernel already demanded.  Padding columns/features
        # are trivial (num_bin=1) and can never split.  Voting scatters
        # only the voted [k, B, 3] block (padded inside the grower).
        if self.hist_agg == "scatter" and strategy != "voting":
            import math

            if plan is None:
                a = self.f_shards * self.d_shards
                if hist_impl == "pallas2":
                    a = math.lcm(a, 32 * max(self.f_shards, 1))
                self.f_pad = -(-self.f_pad // a) * a
                self.g_pad = self.f_pad
            else:
                # EFB: only the bundle-column axis scatters; the shard ->
                # feature assignment rides the scatter_feat table below
                a = self.d_shards
                if hist_impl == "pallas2":
                    a = math.lcm(a, 32)
                self.g_pad = -(-self.g_pad // a) * a

        # transposed [G, n] bin matrix: rows ride the 128-lane minor axis
        # for the histogram contraction (see ops/histogram.py).  Stored
        # uint8 when bins fit (the reference's narrow dense bins,
        # dense_bin.hpp / dense_nbits_bin.hpp): the matrix is re-read every
        # grower round, so width directly scales histogram HBM traffic;
        # the one-hot compare upcasts on the fly
        bin_dtype = np.uint8 if B <= 256 else np.int32
        if self._sparse_mask is not None:
            if cols_src is None:  # COO packing reads host columns
                cols_src = train_data.bins
                dev_src = None
            dense_idx = np.flatnonzero(~self._sparse_mask)
            sparse_idx_cols = np.flatnonzero(self._sparse_mask)
            gd = len(dense_idx)
            # the perfeature pallas kernel chunks its feature grid in
            # 32-multiples — align the DENSE matrix width; the sparse
            # groups never enter that kernel
            gd_pad = -(-gd // 32) * 32 if hist_impl == "pallas2" else gd
            width_sp = (self._local_width if self._partitioned
                        else self.n_pad)
            bins_t = np.zeros((gd_pad, width_sp), dtype=bin_dtype)
            bins_t[:gd, :n] = cols_src[:, dense_idx].T
            zb_np = meta_np["default_bin"]
            Gs = len(sparse_idx_cols)
            # ONE vectorized nonzero pass over the sparse columns,
            # column-blocked to bound the boolean temporary; entries
            # come out sorted by (slot, row), exactly the order the
            # per-column scans produced
            slot_parts, row_parts, bin_parts = [], [], []
            blk = max((1 << 28) // max(n, 1), 1)
            for lo_c in range(0, Gs, blk):
                cols = sparse_idx_cols[lo_c:lo_c + blk]
                sub = cols_src[:, cols]
                g_i, r_i = np.nonzero((sub != zb_np[cols][None, :]).T)
                slot_parts.append((g_i + lo_c).astype(np.int64))
                row_parts.append(r_i.astype(np.int64))
                bin_parts.append(sub[r_i, g_i].astype(np.int32))
            slot = (np.concatenate(slot_parts) if slot_parts
                    else np.zeros(0, np.int64))
            row_id = (np.concatenate(row_parts) if row_parts
                      else np.zeros(0, np.int64))
            binval = (np.concatenate(bin_parts) if bin_parts
                      else np.zeros(0, np.int32))
            # pad row-id = the (local) width (out of range: the
            # partition scatter drops it); pad bin = B (its one-hot row
            # is all-zero, so the clipped histogram gather contributes
            # nothing)
            if self.d_shards > 1:
                # data sharding: per-SHARD tables with shard-local row
                # ids — the leading axis shards over 'data' so each
                # device holds only its block, and the sparse
                # contraction psums like the dense one.  Partitioned
                # ingest: this process's local rows cover exactly its
                # own shards, so it builds [shards_local, Gs, M] and
                # contributes them via put_local; the entry capacity M
                # must still be the GLOBAL max.
                rps = self.n_pad // self.d_shards
                sl = (self.d_shards // jax.process_count()
                      if self._partitioned else self.d_shards)
                shard = row_id // rps
                key = shard * Gs + slot
                counts = np.bincount(key, minlength=sl * Gs)
                max_nnz = int(counts.max()) if counts.size else 0
                if self._partitioned:
                    from ..parallel.topology import host_allgather

                    max_nnz = int(host_allgather(
                        np.asarray([max_nnz], np.int32),
                        name="sparse_table_width").max())
                M = max(128, -(-max_nnz // 128) * 128)
                sp_rows = np.full((sl, Gs, M), rps, np.int32)
                sp_bins = np.full((sl, Gs, M), B, np.int32)
                # stable sort by (shard, slot) keeps rows ascending
                # within each table row, like the per-shard slices did
                order = np.argsort(key, kind="stable")
                k_s = key[order]
                starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
                pos = np.arange(len(k_s)) - starts[k_s]
                sp_rows[shard[order], slot[order], pos] = \
                    row_id[order] - shard[order] * rps
                sp_bins[shard[order], slot[order], pos] = binval[order]
            else:
                counts = np.bincount(slot, minlength=Gs)
                max_nnz = int(counts.max()) if counts.size else 0
                M = max(128, -(-max_nnz // 128) * 128)
                sp_rows = np.full((Gs, M), self.n_pad, np.int32)
                sp_bins = np.full((Gs, M), B, np.int32)
                starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
                pos = np.arange(len(row_id)) - starts[slot]
                sp_rows[slot, pos] = row_id
                sp_bins[slot, pos] = binval
            F_ = self.num_features
            is_sparse = np.zeros(F_, np.int32)
            is_sparse[sparse_idx_cols] = 1
            sparse_slot = np.zeros(F_, np.int32)
            sparse_slot[sparse_idx_cols] = np.arange(Gs)
            dense_col = np.zeros(F_, np.int32)
            dense_col[dense_idx] = np.arange(gd)
            meta_np["is_sparse"] = is_sparse
            meta_np["sparse_slot"] = sparse_slot
            meta_np["dense_col"] = dense_col
            # a known-dense feature id: expand_sparse reads this
            # feature's histogram for exact leaf totals (padded by the
            # meta loop; only element 0 is read)
            meta_np["dense_ref"] = np.full(F_, dense_idx[0], np.int32)
            # feature -> slot in concat(dense columns, sparse groups);
            # padding features (g_pad > F) point at a dense padding
            # column — trivial (num_bin=1), never searched or split
            perm = np.full(self.g_pad, min(gd, gd_pad - 1), np.int32)
            perm[dense_idx] = np.arange(gd)
            perm[sparse_idx_cols] = gd_pad + np.arange(Gs)
            self._sparse_arrays = (sp_rows, sp_bins, perm)
            Log.info(f"sparse storage: {Gs} of {F_} features as COO "
                     f"({M} entry slots), dense matrix "
                     f"{gd_pad}x{self.n_pad}")
        else:
            self._sparse_arrays = None
            # partitioned: only this process's rows, at its local width
            width = self._local_width if self._partitioned else self.n_pad
            if (dev_src is not None and strategy == "serial"
                    and not self.stream_layout):
                # device-side layout: transpose + pad the device-
                # resident ingest matrix in HBM — the host [n, F]
                # matrix never exists on this path
                bins_t = jnp.zeros(
                    (self.g_pad, width),
                    dtype=jnp.uint8 if B <= 256 else jnp.int32)
                bins_t = bins_t.at[:self.num_columns, :n].set(
                    dev_src.T.astype(bins_t.dtype))
            else:
                if cols_src is None:  # parallel placement ships host
                    cols_src = train_data.bins
                bins_t = np.zeros((self.g_pad, width), dtype=bin_dtype)
                bins_t[:self.num_columns, :n] = cols_src.T

        # 4-bit packing (reference dense_nbits_bin.hpp): two rows per
        # byte in a per-block stride layout (row j low nibble, row
        # j + block/2 high nibble) so the pallas kernel unpacks with a
        # nibble mask + lane concat.  Halves the row sweep's DMA traffic.
        # the pack layout's blocks must coincide with the GROWER's blocks,
        # which are derived from the PER-SHARD row count under data
        # sharding — a global-block layout split across shards would
        # decode the wrong rows silently
        local_rows = self.n_pad // self.d_shards
        eff_block = min(block, local_rows)
        self.packed_bins = (
            bool(config.tpu_pack_bins) and B <= 16
            and not self.stream_layout
            and hist_impl in ("pallas", "pallas2") and plan is None
            and self._sparse_arrays is None and not self._partitioned
            and str(config.tpu_partition_impl) in ("select", "vselect")
            and eff_block % 256 == 0 and local_rows % eff_block == 0)
        if self.packed_bins:
            x = bins_t.reshape(self.g_pad, self.n_pad // eff_block, 2,
                               eff_block // 2)
            packed = (x[:, :, 0, :] | (x[:, :, 1, :] << 4)).reshape(
                self.g_pad, self.n_pad // 2)
            # device-laid-out bins_t packs in HBM; host arrays keep the
            # contiguity the kernel's DMA expects
            bins_t = (np.ascontiguousarray(packed)
                      if isinstance(packed, np.ndarray) else packed)

        meta_host = {}
        for k, v in meta_np.items():
            pad_val = 1 if k == "num_bin" else (1.0 if k == "penalty" else 0)
            if self.f_pad != self.num_features:
                v = np.concatenate(
                    [v, np.full(self.f_pad - self.num_features, pad_val,
                                dtype=v.dtype)])
            meta_host[k] = v

        from ..parallel import topology as _topo

        if strategy == "serial":
            self.topology = None
            self.mesh = None
            _topo.activate(None)
            self._place_serial_bins(bins_t, n)
        else:
            self.topology = _topo.make_topology(
                num_data_shards=self.d_shards,
                num_feature_shards=self.f_shards,
                num_hosts=self.hosts,
                partitioned_rows=self._partitioned)
            _topo.activate(self.topology)
            self.mesh = self.topology.mesh
            if self._partitioned:
                # each process contributes only ITS rows to the global
                # arrays (reference pre_partition: rows never leave
                # their machine)
                self.bins_t = put_local(
                    bins_t, bins_sharding(self.mesh, strategy),
                    (bins_t.shape[0], self.n_pad))
                ones = np.zeros(self._local_width, np.float32)
                ones[:n] = 1.0
                self._ones_host = ones
                self._ones_mask = put_local(
                    ones, rows_sharding(self.mesh, strategy),
                    (self.n_pad,))
            else:
                self.bins_t = put_global(
                    bins_t, bins_sharding(self.mesh, strategy))
                ones = np.ones(self.n_pad, np.float32)
                ones[n:] = 0.0
                self._ones_host = ones
                self._ones_mask = put_global(
                    ones, rows_sharding(self.mesh, strategy))
        self.n = n

        meta_cast = {k: (v.astype(np.int32) if v.dtype != np.float32 else v)
                     for k, v in meta_host.items()}
        # multi-host mesh: every array entering the sharded grower must be
        # a GLOBAL jax.Array; cache the shardings train() re-uses per tree
        self._multiproc = self.mesh is not None and jax.process_count() > 1
        # traced mode switches (ops/grower.py MF_*): the real boolean/
        # scalar mode values ride this meta vector so ONE compiled grow
        # program serves every combination; the GrowerParams fields they
        # replace are canonicalized out of the grower cache key below
        meta_cast["mode_flags"] = mode_flags_np(
            quant_round=str(config.tpu_quant_round),
            quant_refit=(quantized
                         and bool(config.tpu_quant_refit_leaves)),
            cegb_tradeoff=float(config.cegb_tradeoff),
            cegb_penalty_split=float(config.cegb_penalty_split))
        if self._multiproc:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._rep_sharding = NamedSharding(self.mesh, P())
            self._rows_shard = rows_sharding(self.mesh, strategy)
            self.meta = {k: put_global(v, self._rep_sharding)
                         for k, v in meta_cast.items()}
        else:
            self.meta = {k: jnp.asarray(v) for k, v in meta_cast.items()}
        if self._sparse_arrays is not None:
            # COO tables ride meta like the CEGB state does (the pad
            # loop above only handles per-feature vectors).  Data-
            # sharded learners shard the per-shard leading axis at
            # placement so no replicated->sharded reshard crosses the
            # program boundary (the CPU gloo backend aborts on those)
            sp_rows, sp_bins, perm = self._sparse_arrays
            if self._multiproc:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P_

                from ..parallel.topology import ROW_AXES

                shard3 = NamedSharding(self.mesh, P_(ROW_AXES))
                if self._partitioned:
                    # this process built only ITS shards' tables
                    gshape = (self.d_shards,) + sp_rows.shape[1:]
                    self.meta["sparse_idx"] = put_local(sp_rows, shard3,
                                                        gshape)
                    self.meta["sparse_bin"] = put_local(sp_bins, shard3,
                                                        gshape)
                else:
                    self.meta["sparse_idx"] = put_global(sp_rows, shard3)
                    self.meta["sparse_bin"] = put_global(sp_bins, shard3)
                self.meta["hist_perm"] = put_global(perm,
                                                    self._rep_sharding)
            else:
                self.meta["sparse_idx"] = jnp.asarray(sp_rows)
                self.meta["sparse_bin"] = jnp.asarray(sp_bins)
                self.meta["hist_perm"] = jnp.asarray(perm)
        if self.hist_agg == "scatter" and plan is not None:
            # static shard -> feature-ids table for the scattered EFB
            # search: shard d owns bundle columns [d*SGc, (d+1)*SGc) and
            # therefore exactly the features bundled into them.  Rows are
            # ascending (so the per-shard argmax keeps the lowest-feature
            # tie-break) and -1-padded to the widest shard's count.
            sgc = self.g_pad // self.d_shards
            bidx = meta_np["bundle_idx"][:self.num_features]
            by_shard = [np.sort(np.flatnonzero(bidx // sgc == d))
                        for d in range(self.d_shards)]
            sf = np.full((self.d_shards,
                          max(1, max(len(l) for l in by_shard))), -1,
                         np.int32)
            for d, l in enumerate(by_shard):
                sf[d, :len(l)] = l
            self.meta["scatter_feat"] = (
                put_global(sf, self._rep_sharding) if self._multiproc
                else jnp.asarray(sf))
        timer.add("layout", time.perf_counter() - _t_layout)

        self.params = GrowerParams(
            num_leaves=max(int(config.num_leaves), 2),
            num_bins=B,
            block_rows=min(block, self.n_pad // self.d_shards
                           if self.d_shards > 1 else self.n_pad),
            precision=precision,
            l1=float(config.lambda_l1),
            l2=float(config.lambda_l2),
            max_delta_step=float(config.max_delta_step),
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian=float(config.min_sum_hessian_in_leaf),
            min_gain_to_split=float(config.min_gain_to_split),
            max_depth=int(config.max_depth),
            has_cat=bool(meta_np["is_categorical"].any()),
            max_cat_threshold=int(config.max_cat_threshold),
            cat_l2=float(config.cat_l2),
            cat_smooth=float(config.cat_smooth),
            max_cat_to_onehot=int(config.max_cat_to_onehot),
            min_data_per_group=float(config.min_data_per_group),
            split_batch=resolve_split_batch(int(config.tpu_split_batch),
                                            int(config.num_leaves)),
            split_batch_alpha=float(config.tpu_split_batch_alpha),
            feature_fraction_bynode=float(config.feature_fraction_bynode),
            has_cegb=has_cegb,
            has_cegb_lazy=has_cegb_lazy,
            cegb_tradeoff=float(config.cegb_tradeoff),
            cegb_penalty_split=float(config.cegb_penalty_split),
            forced=forced,
            hist_impl=hist_impl,
            partition_impl=str(config.tpu_partition_impl),
            has_bundles=plan is not None,
            has_sparse=self._sparse_arrays is not None,
            packed_bins=self.packed_bins,
            ramp=bool(config.tpu_ramp),
            quant_round=str(config.tpu_quant_round),
            quant_refit=(quantized
                         and bool(config.tpu_quant_refit_leaves)),
            # the bucket policy's compile-time lever on the grow program:
            # "wide" ramps the frontier pre-rounds x4 (half the unrolled
            # rounds, bit-identical trees)
            ramp_step=(4 if str(config.tpu_bucket_policy) == "wide"
                       else 2),
            hist_agg=self.hist_agg,
        )
        # quantized leaf refit: the driver must fetch out["leaf_output"]
        # and override the record-replayed leaf values at tree build
        self.refits_leaves = self.params.quant_refit
        if has_cegb_lazy and strategy != "serial":
            # the reference's lazy bitset is learner-local over the full
            # data; under row sharding the paid matrix would need its own
            # collective — reject loudly until that exists
            raise NotImplementedError(
                "cegb_penalty_feature_lazy requires tree_learner=serial")
        # cross-tree CEGB state (reference is_feature_used_in_split_ /
        # feature_used_in_data_ live for the learner's lifetime,
        # cost_effective_gradient_boosting.hpp:33-48)
        if has_cegb:
            zeros_f = np.zeros(self.f_pad, np.float32)
            self._cegb_used = (put_global(zeros_f, self._rep_sharding)
                               if self._multiproc else jnp.asarray(zeros_f))
            self.meta["cegb_used"] = self._cegb_used
            if has_cegb_lazy:
                # bool storage: the reference's bitset is n*F/8 bytes;
                # bool is 8x that but 4x smaller than f32, and the einsum
                # casts per round transiently
                self._cegb_paid = jnp.zeros((self.f_pad, self.n_pad),
                                            jnp.bool_)
                self.meta["cegb_paid"] = self._cegb_paid
        # buffer donation (tpu_donate_buffers): the grower's histogram
        # pool and the step's score buffers are donated to XLA so they
        # are rewritten in place across iterations.  Multi-process runs
        # keep donation off (global-array donation across the gloo CPU
        # test backend is unvalidated); voting keeps its pool shard-LOCAL
        # so only the score buffers donate there.
        self._donate = (bool(config.tpu_donate_buffers)
                        and not self._multiproc)
        self._external_pool = self._donate and strategy != "voting"
        if self._external_pool:
            shape = (self.params.num_leaves, self.g_pad, B, 3)
            pdt = jnp.dtype(pool_dtype(precision))
            sharding = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding

                sharding = NamedSharding(self.mesh, pool_partition_spec(
                    strategy, self.hist_agg == "scatter"))
            self._pool_spec = (shape, pdt, sharding)
        else:
            self._pool_spec = None
        self.reset_pool()
        # the grower cache key is the CANONICAL params (the mode-flag-
        # folded fields normalized away): every run whose structural axes
        # match reuses one grow program, whatever its mode values
        self.grow = make_strategy_grower(
            canonical_params(self.params), self.f_pad, strategy, self.mesh,
            voting_k=int(config.top_k), num_columns=self.g_pad,
            external_pool=self._external_pool)
        self._feature_rng = np.random.default_rng(int(config.feature_fraction_seed))

    def reset_pool(self) -> None:
        """(Re)create the donated histogram-pool buffer as zeros.

        The pool MUST be XLA-owned (jnp.zeros, never
        jnp.asarray(np.zeros(...))): on the CPU backend a device_put of
        aligned host memory is ZERO-COPY — the buffer aliases
        numpy-owned pages, and donating it lets XLA rewrite/free memory
        it does not own (intermittent, alignment-dependent heap
        corruption; reproduced on jaxlib 0.4.x).

        Also the recovery path after a failed DONATING dispatch consumed
        the threaded buffer (gbdt._iter_restore): the pool is
        per-iteration scratch that the grower rewrites wholesale, so a
        zeros replacement is bit-equivalent."""
        if self._pool_spec is None:
            self._pool = None
            return
        shape, pdt, sharding = self._pool_spec
        self._pool = (jnp.zeros(shape, pdt, device=sharding)
                      if sharding is not None else jnp.zeros(shape, pdt))

    def _place_serial_bins(self, bins_t, n: int) -> None:
        """Place the serial-layout transposed bin matrix.

        The resident default commits the whole [g_pad, n_pad] matrix to
        device memory; StreamedTreeLearner overrides this to keep it
        host-resident as fixed-size row blocks (ops/stream.py)."""
        self.bins_t = jnp.asarray(bins_t)
        self._ones_mask = jnp.ones(self.n_pad, jnp.float32).at[n:].set(0.0)

    # ------------------------------------------------------------------
    @staticmethod
    def _resolve_hist_agg(config: Config, strategy: str,
                          d_shards: int) -> str:
        """Effective data-axis histogram aggregation: 'psum' | 'scatter'.

        tpu_hist_agg=auto picks scatter whenever the data axis spans more
        than one device: the reduce-scatter moves half the psum's ICI
        receive bytes, the per-shard histogram pool shrinks by the data-
        shard factor, and the split search stops being repeated P times —
        with int8/int16 decisions bit-identical to psum (associative
        int32 sums + the shared tie-break).  Everywhere without a real
        data axis (serial, pure feature sharding, one data shard) the
        collective degenerates and psum is the plain path."""
        if strategy in ("data", "voting", "data_feature") and d_shards > 1:
            return ("scatter" if str(config.tpu_hist_agg)
                    in ("auto", "scatter") else "psum")
        return "psum"

    @staticmethod
    def _resolve_hist_impl(config: Config, num_bins: int, precision: str,
                           tuned: Optional[dict] = None) -> Tuple[str, int]:
        """Resolve (tpu_hist_impl, tpu_block_rows), honoring "auto"/0.

        `tuned` is the autotune profile entry for this shape bucket
        (utils/autotune.resolve_autotune): its measured winners replace
        the heuristics below wherever the config says "auto"/0 — an
        explicit impl or block always wins over the profile.

        Auto picks the perfeature pallas kernel ("pallas2") on TPU: its
        largest VMEM temporary is a [Bp, block] one-hot (not the flat
        kernel's [F*B, block]), so multi-k-row blocks fit and the kernel
        self-chunks the feature axis when the accumulator would overflow.
        Measured on v5e Higgs-1M (docs/PERF_NOTES.md round-3 sweep, K=25
        hilo + ramp): pallas2/8192 3.14 it/s vs pallas/256 1.82 it/s vs
        xla/16384 1.23 it/s, identical train AUC.  Everywhere else (CPU
        tests, f64 deterministic mode, bin counts too tall for even the
        minimum dtype-tile-wide feature chunk — 32 features for uint8
        bins, 8 for int32) the xla scan at streaming-sized blocks wins.
        """
        impl = str(config.tpu_hist_impl)
        block = int(config.tpu_block_rows)
        if tuned:
            if impl == "auto" and tuned.get("hist_impl"):
                impl = str(tuned["hist_impl"])
            if block <= 0 and int(tuned.get("block_rows", 0) or 0) > 0:
                block = int(tuned["block_rows"])
        if impl == "auto":
            from ..ops.histogram import _PERFEATURE_OUT_BUDGET

            leaves = max(int(config.num_leaves), 2)
            k = min(resolve_split_batch(int(config.tpu_split_batch), leaves),
                    leaves - 1)  # the grower's own clamp (make_grower)
            s = 5 if precision == "hilo" else 3
            ks_pad = -(-(k * s) // 128) * 128
            bp = -(-num_bins // 8) * 8
            # smallest feature chunk the kernel can retreat to: the
            # sublane tile of the bins dtype (uint8 for <=256 bins, else
            # int32 — learner.py bin_dtype / _hist_pallas's step table),
            # so a [step*Bp, K*S] accumulator block must fit the budget;
            # the learner's 32-multiple column pad keeps either divisible
            step = 32 if num_bins <= 256 else 8
            chunk_fits = step * bp * ks_pad * 4 <= _PERFEATURE_OUT_BUDGET
            # an explicit row block must stay Mosaic-lane-aligned for the
            # kernel's [.., block] grid specs, and within the
            # hardware-validated range — the [Bp, block] one-hot and
            # [K*S, block] expanded stats scale with the block, so huge
            # blocks overflow VMEM (the sweep validated up to 16384);
            # out-of-range blocks ride the xla scan
            block_ok = block <= 0 or (block % 128 == 0 and block <= 16384)
            on_tpu = jax.devices()[0].platform == "tpu"
            # f32/f64 stay on xla: auto only picks the validated bf16/hilo
            # kernel shape (an explicit tpu_hist_impl=pallas/pallas2 still
            # honors f32 via Precision.HIGHEST inside _hist_pallas).
            # int8 rides the same kernel (int8 MXU dots, int32 VMEM
            # accumulator; the [3, n] stats plane is leaner than hilo's
            # [5, n]).  int16 is no longer pinned to xla: the
            # mosaic_int16_ok runtime probe (ops/fused.py) compiles and
            # runs a tiny int16 perfeature kernel against the xla oracle
            # on THIS backend, so auto promotes int16 exactly where the
            # Mosaic int16 dot is hardware-validated and falls back
            # loudly (probe logs a warning) where it is not
            mosaic_ok = precision in ("hilo", "bf16", "int8")
            if precision == "int16" and on_tpu and chunk_fits and block_ok:
                from ..ops.fused import mosaic_int16_ok

                mosaic_ok = mosaic_int16_ok()
            impl = ("pallas2" if on_tpu and chunk_fits and block_ok
                    and mosaic_ok else "xla")
            # fused promotion: the quantized precisions additionally run
            # the split scan inside the grow megakernel when the traced
            # scan validates against the unfused oracle on this backend
            # (fused_scan_ok — again a loud fallback, never a silent one)
            if impl == "pallas2" and precision in ("int8", "int16"):
                from ..ops.fused import fused_scan_ok

                if fused_scan_ok(precision):
                    impl = "fused"
        if block <= 0:
            block = {"pallas": 256, "pallas2": 8192,
                     "fused": 8192}.get(impl, 16384)
        return impl, block

    @staticmethod
    def _resolve_precision(config: Config) -> str:
        """Histogram precision, honoring deterministic mode.

        deterministic=true accumulates everything in f64 (the reference's
        HistogramBinEntry representation, bin.h:33-40) so serial and
        data-parallel decisions agree exactly; requires jax x64, which is
        enabled here process-wide.  The quantized precisions (int8/int16)
        are ALREADY reduction-order invariant — int32 sums are associative
        — so deterministic=true keeps them as-is at full speed instead of
        forcing the slow f64 path (the recommended deterministic mode)."""
        precision = str(config.tpu_hist_precision)
        if not bool(config.deterministic):
            return precision
        if precision in ("int8", "int16"):
            return precision
        jax.config.update("jax_enable_x64", True)
        if str(config.tpu_hist_impl) in ("pallas", "pallas2", "fused"):
            raise ValueError(
                "deterministic=true requires tpu_hist_impl=xla")
        return "f64"

    @staticmethod
    def _parse_forced_splits(config: Config, train_data: TrainingData
                             ) -> tuple:
        """forcedsplits_filename JSON -> static BFS (parent_leaf, feature,
        thr_bin) triples for the grower (reference ForceSplits reads the
        same nested {feature, threshold, left, right} JSON,
        serial_tree_learner.cpp:617-669)."""
        path = str(config.forcedsplits_filename or "")
        if not path:
            return ()
        import json

        with open(path) as f:
            root = json.load(f)
        pos_of = {col: j for j, col in enumerate(train_data.used_feature_idx)}
        out = []
        queue = [(root, 0)]
        while queue and len(out) < max(int(config.num_leaves) - 1, 0):
            node, leaf = queue.pop(0)
            real_f = int(node["feature"])
            if real_f not in pos_of:
                raise ValueError(
                    f"forced split on unused/trivial feature {real_f}")
            inner = pos_of[real_f]
            mapper = train_data.mappers[real_f]
            from ..io.bin_mapper import BinType

            if mapper.bin_type != BinType.NUMERICAL:
                raise NotImplementedError(
                    "forced splits on categorical features are not "
                    "supported")
            thr_bin = int(mapper.value_to_bin(float(node["threshold"])))
            i = len(out)
            out.append((leaf, inner, thr_bin))
            # left child keeps the parent's leaf id; right child is the
            # (i+1)-th leaf created (the grower's record/new-leaf contract)
            if isinstance(node.get("left"), dict) and "feature" in node["left"]:
                queue.append((node["left"], leaf))
            if isinstance(node.get("right"), dict) and "feature" in node["right"]:
                queue.append((node["right"], i + 1))
        return tuple(out)

    def sample_features(self) -> jnp.ndarray:
        """Per-tree feature_fraction mask (reference GetUsedFeatures,
        serial_tree_learner.cpp:271-319).  Sized to the padded feature axis;
        padding features stay masked off."""
        frac = float(self.config.feature_fraction)
        F = self.num_features
        mask = np.zeros(self.f_pad, np.float32)
        if frac < 1.0:
            k = max(1, int(np.ceil(F * frac)))
            used = self._feature_rng.choice(F, size=k, replace=False)
            mask[used] = 1.0
        else:
            mask[:F] = 1.0
        return jnp.asarray(mask)

    def pad_vector(self, v: jnp.ndarray) -> jnp.ndarray:
        if v.shape[0] == self.n_pad:
            return v
        return jnp.zeros(self.n_pad, v.dtype).at[:v.shape[0]].set(v)

    # ------------------------------------------------------------------
    def make_train_step(self, grad_fn, learning_rate: float,
                        bagging: Optional[Dict] = None,
                        goss: Optional[Dict] = None):
        """Fuse gradients + tree growth + train-score update into ONE device
        program per iteration.

        On tunneled TPU attachments every host<->device round trip costs tens
        of ms, so the driver must dispatch asynchronously and never sync on
        the hot path: RNG keys thread through device state, bagging and
        feature-fraction masks are sampled on device, and the only per-tree
        artifact is the packed [L-1, 15] record array (fetched lazily).

        grad_fn: (scores [k, n]) -> (grad [k, n], hess [k, n]) pure device fn.
        Returns step(scores, key, class_id_static) ->
            (records, new_scores, leaf_ids, leaf_output, new_key).
        """
        n, n_pad = self.n, self.n_pad
        frac = 1.0 if bagging is None else bagging.get("fraction", 1.0)
        pos_frac = 1.0 if bagging is None else bagging.get("pos_fraction", 1.0)
        neg_frac = 1.0 if bagging is None else bagging.get("neg_fraction", 1.0)
        is_pos = None
        if bagging is not None and (pos_frac < 1.0 or neg_frac < 1.0):
            is_pos = jnp.asarray(bagging["is_pos"])
        feature_frac = float(self.config.feature_fraction)
        ones_mask = self._ones_mask
        F = self.num_features
        f_pad = self.f_pad
        grow = self.grow
        meta = self.meta
        bins_t = self.bins_t

        goss_top_k = goss_other_k = 0
        if goss is not None:
            goss_top_k = max(1, int(n * float(goss["top_rate"])))
            goss_other_k = max(1, int(n * float(goss["other_rate"])))

        def _pre(grad_scores, key, bag_key, class_id, refresh_bag,
                 goss_on):
            # grad_scores = scores at ITERATION start: all classes' gradients
            # come from the same snapshot, like the reference's single
            # Boosting() call per iteration (gbdt.cpp:150-158); `scores`
            # accumulates the per-class deltas within the iteration.
            # class_id and refresh_bag are TRACED (shape-stability: one
            # compiled step serves every class and both sides of the
            # bagging_freq boundary — previously each was a static key
            # multiplying the program count)
            # named_scope: the host-span vocabulary (boost / bagging /
            # score_update) mirrored into xprof device traces
            with jax.named_scope("boost"):
                grad, hess = grad_fn(grad_scores)
            g = grad[class_id] if grad.ndim == 2 else grad
            h = hess[class_id] if hess.ndim == 2 else hess
            g = jnp.zeros(n_pad, jnp.float32).at[:n].set(g[:n])
            h = jnp.zeros(n_pad, jnp.float32).at[:n].set(h[:n])

            key, kf = jax.random.split(key)
            bag_key = jnp.where(jnp.asarray(refresh_bag),
                                jax.random.split(bag_key)[0], bag_key)

            def bag_uniform(k, salt):
                # per-row uniforms keyed on the GLOBAL row index (PCG
                # hash, like the quantization rounding) — NOT
                # jax.random.uniform(k, (n_pad,)), whose threefry
                # counters pair across array halves so every value
                # changes with the total padded length.  n_pad differs
                # between serial and sharded layouts (per-shard padding),
                # which made bagging masks topology-dependent and broke
                # the cross-shard bitwise contract (ROADMAP item 7).
                # Precondition: iota == global row index, which holds
                # because this fused step only exists single-process
                # (_maybe_make_train_step gates on not _multiproc) and
                # the single-process layout is compact-at-front (rows
                # [0, n) contiguous, padding only at the tail) — the
                # partitioned multihost layout with interior per-host
                # padding rides the sync path's host-global numpy mask
                sa, sb = key_words(k)
                return hashed_uniform(
                    jax.lax.iota(jnp.uint32, n_pad), sa, sb, salt)

            mask = ones_mask
            if goss_on:
                # GOSS on device (reference goss.hpp:91-139 BaggingHelper):
                # keep the top_rate rows by sum_k |g*h|, Bernoulli-sample
                # other_rate of the rest and upscale their grad/hess by
                # (n - top_k) / other_k.  The reference samples exactly
                # other_k without replacement; the Bernoulli form has the
                # same expectation and is XLA-friendly.
                if grad.ndim == 2:
                    gh_all = jnp.sum(jnp.abs(grad * hess), axis=0)
                else:
                    gh_all = jnp.abs(grad * hess)
                gh = jnp.full(n_pad, -1.0, jnp.float32).at[:n].set(gh_all[:n])
                thr = jnp.sort(gh)[n_pad - goss_top_k]
                keep_top = gh >= thr
                bag_key = jax.random.split(bag_key)[0]
                r = bag_uniform(bag_key, 0x60553)
                p_other = goss_other_k / max(n - goss_top_k, 1)
                keep_other = (~keep_top) & (r < p_other)
                multiply = (n - goss_top_k) / goss_other_k
                scale = jnp.where(keep_other, multiply, 1.0)
                g = g * scale
                h = h * scale
                mask = mask * (keep_top | keep_other).astype(jnp.float32)
            elif is_pos is not None:
                r = bag_uniform(bag_key, 0xBA66)
                keep = jnp.where(is_pos, r < pos_frac, r < neg_frac)
                mask = mask * keep.astype(jnp.float32)
            elif frac < 1.0:
                r = bag_uniform(bag_key, 0xBA66)
                mask = mask * (r < frac).astype(jnp.float32)
            fmask = jnp.zeros(f_pad, jnp.float32).at[:F].set(1.0)
            if feature_frac < 1.0:
                k_used = max(1, int(np.ceil(F * feature_frac)))
                perm = jax.random.permutation(kf, F)
                fmask = jnp.zeros(f_pad, jnp.float32).at[perm[:k_used]].set(1.0)

            key, k_node = jax.random.split(key)
            return g, h, mask, fmask, k_node, key, bag_key

        def _post(scores, records, leaf_ids, leaf_output, class_id):
            with jax.named_scope("score_update"):
                any_split = records[0, 14] > 0.5  # REC_DID_SPLIT
                # scale the [L] leaf vector FIRST, then gather: the
                # per-row path is gather + ONE correctly-rounded add.
                # The per-row `leaf_output[ids] * lr + scores` form left
                # a mul+add chain that XLA/LLVM may (or may not)
                # contract into an FMA depending on the surrounding
                # program — serial and shard_map programs contracted
                # differently, drifting scores one ulp apart at the
                # SAME trees and breaking the cross-topology bitwise
                # contract (ROADMAP item 7's second root cause)
                scaled = jnp.where(any_split,
                                   leaf_output * learning_rate, 0.0)
                new_scores = scores.at[class_id, :].add(
                    scaled[leaf_ids[:n]])
            return new_scores, leaf_ids[:n]

        external_pool = self._external_pool
        donate = self._donate

        def make_step(pre_fn, post_fn):
            # ONE step body shared by both modes: pre -> grow -> post.
            # `pool` is the donated histogram-pool buffer (None when
            # donation is off): grow rewrites it in place and the caller
            # threads the returned buffer into the next call.
            def step(grad_scores, scores, key, bag_key, pool, class_id,
                     refresh_bag, goss_on=False):
                g, h, mask, fmask, k_node, key, bag_key = pre_fn(
                    grad_scores, key, bag_key, class_id=class_id,
                    refresh_bag=refresh_bag, goss_on=goss_on)
                if external_pool:
                    out = grow(bins_t, g, h, mask, fmask, meta, k_node,
                               pool)
                    pool = out["pool"]
                else:
                    out = grow(bins_t, g, h, mask, fmask, meta, k_node)
                new_scores, lids = post_fn(scores, out["records"],
                                           out["leaf_ids"],
                                           out["leaf_output"],
                                           class_id=class_id)
                return (out["records"], new_scores, lids,
                        out["leaf_output"], key, bag_key, pool)
            return step

        if int(self.config.tpu_shape_buckets) > 0 \
                and self.strategy == "serial":
            # shape-bucketed pipeline (serial strategy only): keep the
            # n-shaped grad/score glue in SMALL separate programs
            # (seconds to compile) so the big bucketed grower program is
            # the only expensive compile — a new dataset in the same
            # bucket reuses it from the persistent cache.  All three
            # dispatches stay async; no host sync is introduced.
            # Parallel strategies keep the fused program: their sharded
            # outputs (leaf_ids on the 'data' axis) would reshard across
            # the program boundary, which the CPU-collectives test
            # backend aborts on — and multi-chip wants the fusion anyway.
            # Only goss_on stays static (its sort is structural work);
            # the grower's own ledgered jit donates the pool here and
            # post donates the scores buffer.  pre/post stay OFF the
            # ledger by design: they are per-objective closures (label
            # arrays captured) that re-trace per Booster in milliseconds
            # — the ledger tracks the programs that dominate compile wall
            # (grower, fused step, predict/binning/histogram kernels).
            pre_j = jax.jit(_pre, static_argnames=("goss_on",))  # graftlint: disable=J201 per-objective closure, deliberately off-ledger (see comment above)
            post_j = jax.jit(_post,  # graftlint: disable=J201 per-objective closure, deliberately off-ledger (see comment above)
                             donate_argnums=((0,) if donate else ()))
            return make_step(pre_j, post_j)
        # exact-shape mode (tpu_shape_buckets=0): ONE fused program —
        # the round-3 hardware-validated hot path, bit-identical.
        # Donation at the fused boundary: scores (arg 1) and the pool
        # (arg 4) are rewritten in place by XLA.  The fused jit is the
        # ledger site here (the grower's own jit is traced inline).
        from ..utils.compile_ledger import ledger_jit

        dn = []
        if donate:
            dn.append(1)
        if external_pool:
            dn.append(4)
        return ledger_jit(make_step(_pre, _post), site="learner.step",
                          static_argnames=("goss_on",),
                          donate_argnums=tuple(dn))

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              row_mask: Optional[jnp.ndarray] = None
              ) -> Tuple[Tree, jnp.ndarray, Dict]:
        """Grow one tree. Returns (tree, leaf_ids[n] device, raw grower out)."""
        # RNG consumption order must stay sample_features() THEN the key
        # draw — the order the serial call has always used — or seeded
        # runs change trees
        fmask = self.sample_features()
        key = jax.random.PRNGKey(int(self._feature_rng.integers(2 ** 31)))
        if self.params.has_cegb:
            # thread the cross-tree CEGB state through this tree's meta
            self.meta = dict(self.meta)
            self.meta["cegb_used"] = self._cegb_used
            if self.params.has_cegb_lazy:
                self.meta["cegb_paid"] = self._cegb_paid
        if self._multiproc:
            # shard the per-row vectors globally, replicate the small
            # ones.  Partitioned: the row vectors are LOCAL (this
            # process's rows only) and placed as local shards.
            width = (self._local_width if self._partitioned
                     else self.n_pad)

            def pad_host(v):
                out_v = np.zeros(width, np.float32)
                out_v[:np.shape(v)[0]] = np.asarray(v, np.float32)
                return out_v

            def place_rows(v):
                if self._partitioned:
                    return put_local(v, self._rows_shard, (self.n_pad,))
                return put_global(v, self._rows_shard)

            mask_np = self._ones_host if row_mask is None else \
                self._ones_host * pad_host(row_mask)
            out = self.grow(self.bins_t,
                            place_rows(pad_host(grad)),
                            place_rows(pad_host(hess)),
                            place_rows(mask_np),
                            put_global(np.asarray(fmask),
                                       self._rep_sharding),
                            self.meta,
                            put_global(np.asarray(key), self._rep_sharding))
        else:
            mask = self._ones_mask if row_mask is None else \
                self.pad_vector(row_mask) * self._ones_mask
            if self._external_pool:
                out = self.grow(self.bins_t, self.pad_vector(grad),
                                self.pad_vector(hess), mask, fmask,
                                self.meta, key, self._pool)
                self._pool = out["pool"]
            else:
                out = self.grow(self.bins_t, self.pad_vector(grad),
                                self.pad_vector(hess), mask, fmask,
                                self.meta, key)
        if self.params.has_cegb:
            # harvest the updated state for the NEXT tree (async device
            # arrays; no host sync)
            self._cegb_used = out["cegb_used"]
            if self.params.has_cegb_lazy:
                self._cegb_paid = out["cegb_paid"]
        tree = self.build_tree(out)
        if self._multiproc:
            if self._partitioned:
                # each process keeps only ITS rows' leaf ids: the score
                # state is local, so pull the addressable shards in
                # global row order and trim the pad
                shards = sorted(out["leaf_ids"].addressable_shards,
                                key=lambda s: s.index[0].start or 0)
                lids = np.concatenate(
                    [np.asarray(jax.device_get(s.data)).ravel()
                     for s in shards])[:self.n]
                return tree, jnp.asarray(lids), out
            # reassemble the row-sharded leaf ids on every host: the GBDT
            # driver's score updates and renew paths operate on LOCAL
            # arrays (identical on all ranks), and a non-addressable
            # global array cannot be device_get there
            from ..parallel.topology import host_device_allgather

            # the per-iteration hot collective: a dead peer here is the
            # canonical distributed-GBDT hang, so the watchdog matters
            # most at this site
            lids = host_device_allgather(
                out["leaf_ids"], name="leaf_id_allgather")[:self.n]
            return tree, jnp.asarray(lids), out
        return tree, out["leaf_ids"][:self.n], out

    def build_tree(self, out: Dict) -> Tree:
        """Replay device split records into a reference-compatible Tree."""
        fetch = [out["records"]]
        if self.refits_leaves:
            fetch.append(out["leaf_output"])
        got = jax.device_get(fetch)  # one fetch
        rec = np.asarray(got[0])
        leaf_out = np.asarray(got[1]) if self.refits_leaves else None
        return self.build_tree_from_records(rec, leaf_out)

    def build_tree_from_records(self, rec: np.ndarray,
                                leaf_output: Optional[np.ndarray] = None
                                ) -> Tree:
        from ..ops import grower as G
        L = self.params.num_leaves
        tree = Tree(L)
        used = self.td.used_feature_idx
        mappers = self.td.mappers
        missing = self.meta_np["missing_type"]
        for s in range(rec.shape[0]):
            row = rec[s]
            if row[G.REC_DID_SPLIT] < 0.5:
                break
            f = int(row[G.REC_FEATURE])
            thr_bin = int(row[G.REC_THRESHOLD])
            real_f = used[f]
            common = dict(
                leaf=int(row[G.REC_LEAF]),
                feature_inner=f,
                real_feature=real_f,
                left_value=float(row[G.REC_LEFT_OUTPUT]),
                right_value=float(row[G.REC_RIGHT_OUTPUT]),
                left_cnt=int(round(float(row[G.REC_LEFT_COUNT]))),
                right_cnt=int(round(float(row[G.REC_RIGHT_COUNT]))),
                left_weight=float(row[G.REC_LEFT_WEIGHT]),
                right_weight=float(row[G.REC_RIGHT_WEIGHT]),
                gain=float(row[G.REC_GAIN]),
                missing_type=int(missing[f]))
            if row[G.REC_IS_CAT] > 0.5:
                # bins routed left -> bin bitset + raw-category bitset
                # (Tree::SplitCategorical, reference tree.h:60-85)
                bins_left = np.nonzero(row[G.REC_WIDTH:] > 0.5)[0]
                cats_left = [mappers[real_f].bin_2_categorical[b]
                             for b in bins_left]
                tree.split_categorical(
                    threshold_bins=_to_bitset(bins_left),
                    thresholds=_to_bitset(cats_left),
                    **common)
            else:
                tree.split(
                    threshold_bin=thr_bin,
                    threshold_double=mappers[real_f].bin_to_value(thr_bin),
                    default_left=row[G.REC_DEFAULT_LEFT] > 0.5,
                    **common)
        if leaf_output is not None and tree.num_leaves > 1:
            # quantized leaf refit (GrowerParams.quant_refit): the grower
            # leaf ids ARE the Tree leaf indices (left child keeps the
            # parent's id, right child takes the next fresh id — the same
            # contract the record replay above follows), so the device-
            # refitted outputs overwrite the record values positionally
            tree.leaf_value[:tree.num_leaves] = np.asarray(
                leaf_output[:tree.num_leaves], np.float64)
        return tree


class StreamedTreeLearner(TPUTreeLearner):
    """Out-of-core serial learner: host-resident bins, blocked H2D.

    Same construction surface as TPUTreeLearner, but the transposed bin
    matrix never lands on device as a whole — `_place_serial_bins`
    partitions it into C-contiguous host row blocks and train() drives
    the streamed grower (ops/stream.py), which double-buffers each
    block's H2D copy under the previous block's histogram contraction.
    For int8/int16 precisions the resulting model files are
    BYTE-IDENTICAL to the resident layout's (int32 histogram sums are
    associative across blocks; same n_pad, same quantization grid, same
    stochastic-rounding hash on GLOBAL row indices).

    Restrictions are validated loudly at construction (StreamGrower /
    stream_supported): serial only, numerical only, no EFB / sparse /
    CEGB / forced splits / per-node sampling / packed bins.
    """
    stream_layout = True

    def __init__(self, config: Config, train_data: TrainingData):
        if resolve_tree_learner(config.tree_learner) != "serial":
            raise NotImplementedError(
                "tpu_stream_mode=streamed requires tree_learner=serial")
        super().__init__(config, train_data)
        from ..ops.stream import StreamGrower

        # the resident external-pool/donation machinery is bypassed: the
        # streamed round state owns its pool (stream.root_finish) and
        # per-program donation is wired inside ops/stream.py
        self._donate = False
        self._external_pool = False
        self._stream = StreamGrower(
            self.params, self.g_pad, self.n_pad, self._stream_R,
            double_buffer=bool(config.tpu_stream_double_buffer),
            goss_top=float(config.tpu_stream_goss_top),
            goss_other=float(config.tpu_stream_goss_other))
        Log.info(
            f"streamed layout: {len(self._host_blocks)} host blocks x "
            f"{self._stream_R} rows "
            f"({self._host_blocks[0].nbytes >> 20} MiB/block, "
            f"double_buffer={self._stream.double_buffer})")

    def reset_pool(self) -> None:
        # no external donated pool: the streamed grower's pool lives in
        # its device round state and is rebuilt per tree
        self._pool_spec = None
        self._pool = None

    def _place_serial_bins(self, bins_t, n: int) -> None:
        from ..ops.stream import make_host_blocks, resolve_stream_rows
        from ..utils import membudget

        if not isinstance(bins_t, np.ndarray):
            # defensive: the device-transpose fast path is gated off for
            # stream_layout, so this only fires on exotic ingest sources
            bins_t = np.asarray(bins_t)
        precision = self._resolve_precision(self.config)
        _, block = self._resolve_hist_impl(self.config, self.num_bins,
                                           precision)
        self._stream_R = resolve_stream_rows(
            int(self.config.tpu_stream_block_rows), self.n_pad,
            bytes_per_row=int(bins_t.shape[0]) * bins_t.dtype.itemsize,
            inner_block=min(block, self.n_pad),
            budget_bytes=membudget.budget_bytes(self.config))
        self._host_blocks = make_host_blocks(bins_t, self._stream_R)
        self.bins_t = None  # never device-resident on this layout
        self._ones_mask = jnp.ones(self.n_pad, jnp.float32).at[n:].set(0.0)

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              row_mask: Optional[jnp.ndarray] = None
              ) -> Tuple[Tree, jnp.ndarray, Dict]:
        """Grow one tree via the streamed grower.

        RNG consumption order (sample_features THEN the key draw) is the
        resident train()'s — seeded streamed and resident runs consume
        identical randomness, which the bitwise-equality tests pin."""
        fmask = self.sample_features()
        key = jax.random.PRNGKey(int(self._feature_rng.integers(2 ** 31)))
        mask = self._ones_mask if row_mask is None else \
            self.pad_vector(row_mask) * self._ones_mask
        out = self._stream.grow(self._host_blocks, self.pad_vector(grad),
                                self.pad_vector(hess), mask, fmask,
                                self.meta, key)
        tree = self.build_tree(out)
        return tree, out["leaf_ids"][:self.n], out

    @property
    def stream_stats(self) -> Dict[str, float]:
        """Last tree's streaming telemetry (overlap %, H2D wall, blocks
        streamed/skipped) — read by bench.py and perf_probe stream."""
        return dict(self._stream.last_stats)


def make_tree_learner(config: Config,
                      train_data: TrainingData) -> TPUTreeLearner:
    """Layout-dispatching learner constructor — gbdt.py's single entry
    point.  ``tpu_stream_mode`` picks resident (the classic
    device-resident matrix), streamed (host-resident blocks), or auto,
    where membudget.select_layout keeps the resident layout unless its
    pre-construction estimate says the binned matrix would blow the HBM
    budget AND the run is streamable."""
    from ..utils import membudget

    if membudget.select_layout(config, train_data) == "streamed":
        return StreamedTreeLearner(config, train_data)
    return TPUTreeLearner(config, train_data)
