"""Evaluation metrics (reference src/metric/*.hpp).

Metrics evaluate on host numpy arrays (scores come off-device once per
`metric_freq` iterations, which is negligible next to histogram work).
Each metric reports (name, value, higher_is_better).

Distribution-aware (SURVEY §2.6; reference Network::GlobalSyncUp*,
include/LightGBM/network.h:168-275): in a multi-process jax.distributed
run every metric reduces its sufficient statistics across ranks via
parallel.metric_sync, so all ranks report the GLOBAL value and early
stopping fires at the same iteration everywhere.  Single-process runs
pay nothing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..io.dataset import Metadata


class Metric:
    name = "none"
    higher_is_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64)
        self.weight = (None if metadata.weight is None
                       else np.asarray(metadata.weight, np.float64))
        self.sum_weights = (float(self.weight.sum()) if self.weight is not None
                            else float(num_data))

    def eval(self, score: np.ndarray, objective) -> float:
        """score: [k, n] raw scores."""
        raise NotImplementedError

    def eval_all(self, score: np.ndarray, objective) -> List[Tuple[str, float]]:
        """Multi-value interface (e.g. ndcg@1..5 report one value per k,
        reference NDCGMetric::Eval rank_metric.hpp:93).  Default: one value."""
        return [(self.name, self.eval(score, objective))]


def _avg(loss: np.ndarray, weight: Optional[np.ndarray], sum_w: float) -> float:
    """Weighted average with the (numerator, denominator) pair summed
    across processes — both stay LOCAL sums until here, so the division
    happens on the global statistics on every rank."""
    from ..parallel.metric_sync import sync_sums

    num = float(loss.sum()) if weight is None else float((loss * weight).sum())
    g_num, g_den = sync_sums([num, float(sum_w)])
    return float(g_num / g_den)


class L2Metric(Metric):
    name = "l2"

    def eval(self, score, objective):
        pred = score[0]
        if objective is not None:
            pred = objective.convert_output(pred)
        return _avg((self.label - pred) ** 2, self.weight, self.sum_weights)


class RMSEMetric(L2Metric):
    name = "rmse"

    def eval(self, score, objective):
        return float(np.sqrt(super().eval(score, objective)))


class L1Metric(Metric):
    name = "l1"

    def eval(self, score, objective):
        pred = score[0]
        if objective is not None:
            pred = objective.convert_output(pred)
        return _avg(np.abs(self.label - pred), self.weight, self.sum_weights)


class BinaryLoglossMetric(Metric):
    """reference src/metric/binary_metric.hpp (BinaryLoglossMetric)."""
    name = "binary_logloss"

    def eval(self, score, objective):
        prob = objective.convert_output(score[0]) if objective is not None \
            else 1.0 / (1.0 + np.exp(-score[0]))
        prob = np.clip(prob, 1e-15, 1 - 1e-15)
        is_pos = self.label > 0
        loss = np.where(is_pos, -np.log(prob), -np.log(1.0 - prob))
        return _avg(loss, self.weight, self.sum_weights)


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score, objective):
        prob = objective.convert_output(score[0]) if objective is not None \
            else 1.0 / (1.0 + np.exp(-score[0]))
        is_pos = self.label > 0
        err = np.where(is_pos, prob <= 0.5, prob > 0.5).astype(np.float64)
        return _avg(err, self.weight, self.sum_weights)


class AUCMetric(Metric):
    """reference src/metric/binary_metric.hpp AUCMetric (weighted rank sum)."""
    name = "auc"
    higher_is_better = True

    def eval(self, score, objective):
        from ..parallel.metric_sync import process_count, sync_concat

        s = score[0]
        label = self.label
        weight = self.weight
        if process_count() > 1:
            # AUC is a pairwise rank statistic with no per-rank sufficient
            # sum — merge the raw (score, label, weight) columns exactly
            # across ranks, then rank globally (VERDICT r4 #4's "exact
            # merge" option)
            s, label, weight = sync_concat(
                s, label,
                weight if weight is not None else np.ones_like(s))
        order = np.argsort(s, kind="stable")
        sorted_score = s[order]
        sorted_pos = (label[order] > 0).astype(np.float64)
        w = (weight[order] if weight is not None
             else np.ones_like(sorted_pos))
        pos_w = sorted_pos * w
        neg_w = (1.0 - sorted_pos) * w
        # group ties: same score -> same average rank contribution
        boundaries = np.flatnonzero(np.diff(sorted_score)) + 1
        group_id = np.zeros(len(s), dtype=np.int64)
        group_id[boundaries] = 1
        group_id = np.cumsum(group_id)
        num_groups = group_id[-1] + 1 if len(s) else 0
        pos_per_group = np.bincount(group_id, weights=pos_w, minlength=num_groups)
        neg_per_group = np.bincount(group_id, weights=neg_w, minlength=num_groups)
        neg_below = np.concatenate([[0.0], np.cumsum(neg_per_group)[:-1]])
        auc_sum = (pos_per_group * (neg_below + 0.5 * neg_per_group)).sum()
        total_pos = pos_w.sum()
        total_neg = neg_w.sum()
        if total_pos == 0 or total_neg == 0:
            return 1.0
        return float(auc_sum / (total_pos * total_neg))


_METRICS: Dict[str, type] = {}
for _cls in (L2Metric, RMSEMetric, L1Metric, BinaryLoglossMetric,
             BinaryErrorMetric, AUCMetric):
    _METRICS[_cls.name] = _cls

_METRIC_ALIASES = {
    "mse": "l2", "mean_squared_error": "l2", "regression": "l2",
    "regression_l2": "l2", "l2_root": "rmse", "root_mean_squared_error": "rmse",
    "mae": "l1", "mean_absolute_error": "l1", "regression_l1": "l1",
    "binary": "binary_logloss",
}

DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "quantile": "quantile", "mape": "mape",
    "gamma": "gamma", "tweedie": "tweedie", "binary": "binary_logloss",
    "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy", "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    from . import metrics_ext  # noqa: F401  (registers the extended zoo)
    name = _METRIC_ALIASES.get(name, name)
    cls = _METRICS.get(name)
    return None if cls is None else cls(config)


def create_metrics(config: Config, objective_name: str) -> List[Metric]:
    names = list(config.metric)
    if not names:
        default = DEFAULT_METRIC_FOR_OBJECTIVE.get(objective_name)
        names = [default] if default else []
    out = []
    seen = set()
    for n in names:
        n = n.strip().lower()
        if n in ("", "none", "null", "na", "custom"):
            continue
        n = _METRIC_ALIASES.get(n, n)
        if n in seen:
            continue
        seen.add(n)
        m = create_metric(n, config)
        if m is None:
            raise ValueError(f"unknown metric {n!r}")
        out.append(m)
    return out


def register_metric(cls):
    _METRICS[cls.name] = cls
    return cls
