"""Loader + marshalling for the native runtime (lib_lightgbm_tpu.so).

The shared library carries the LGBM_* C ABI (src/capi/
lightgbm_tpu_c_api.cpp) and the OpenMP forest predictor (src/capi/
forest_predictor.cpp) — the native pieces of the runtime, mirroring where
the reference keeps its prediction hot loop in C++ (reference
src/boosting/gbdt_prediction.cpp).  The library is optional: everything
falls back to the numpy implementations when it is absent or the
toolchain can't build it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_CANDIDATES = (
    os.path.join(_REPO, "build", "lib_lightgbm_tpu.so"),
    os.path.join(_REPO, "lib_lightgbm_tpu.so"),
)
_lib = None
_lib_tried = False


def _stale(lib_path: str) -> bool:
    """True when any C++ source is newer than the built library."""
    src_dir = os.path.join(_REPO, "src", "capi")
    try:
        lib_mtime = os.path.getmtime(lib_path)
        for name in os.listdir(src_dir):
            if name.endswith((".cpp", ".h", ".hpp")):
                if os.path.getmtime(os.path.join(src_dir, name)) > lib_mtime:
                    return True
    except OSError:
        return False
    return False


def native_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use when possible."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    existing = next((p for p in _LIB_CANDIDATES if os.path.exists(p)), None)
    path = existing
    no_build = os.environ.get("LIGHTGBM_TPU_NO_BUILD", "") == "1"
    if path is not None and _stale(path) and not no_build:
        # a semantic fix to the C++ must not be masked by a cached build;
        # with rebuilds disabled the existing lib stays in use (warned)
        path = None
    if path is None and not no_build:
        out_dir = os.path.join(_REPO, "build")
        os.makedirs(out_dir, exist_ok=True)
        build = os.path.join(_REPO, "src", "capi", "build.sh")
        try:
            r = subprocess.run([build, out_dir], capture_output=True,
                               timeout=240)
            if r.returncode == 0:
                path = _LIB_CANDIDATES[0]
        except Exception:
            path = None
    if path is None and existing is not None:
        # rebuild failed (or skipped): better a stale native lib than the
        # slow fallback — the staleness is logged for the record
        from .utils.log import Log

        Log.warning(f"using possibly-stale native lib {existing}")
        path = existing
    if path is None:
        return None
    try:
        _lib = ctypes.CDLL(path)
    except OSError:
        _lib = None
    return _lib


class ForestTables:
    """All trees' node tables concatenated for one native call.

    Rebuilt (cheaply, numpy concatenation) whenever the model list grows;
    GBDT caches an instance keyed by len(models).
    """

    def __init__(self, trees: List):
        T = len(trees)
        self.num_trees = T
        no, lo, cbo, cwo = [0], [0], [0], [0]
        sf, th, dt, lc, rc, lv, cb, cw = [], [], [], [], [], [], [], []
        for t in trees:
            ni = max(t.num_leaves - 1, 0)
            no.append(no[-1] + ni)
            lo.append(lo[-1] + t.num_leaves)
            sf.append(t.split_feature[:ni])
            th.append(t.threshold[:ni])
            dt.append(t.decision_type[:ni])
            lc.append(t.left_child[:ni])
            rc.append(t.right_child[:ni])
            lv.append(t.leaf_value[:t.num_leaves])
            cb.append(np.asarray(t.cat_boundaries, np.int32))
            cw.append(np.asarray(t.cat_threshold, np.uint32))
            cbo.append(cbo[-1] + len(t.cat_boundaries))
            cwo.append(cwo[-1] + len(t.cat_threshold))

        def cat_(parts, dtype):
            return (np.ascontiguousarray(np.concatenate(parts), dtype=dtype)
                    if parts else np.zeros(0, dtype))

        self.node_offset = np.asarray(no, np.int32)
        self.leaf_offset = np.asarray(lo, np.int32)
        self.split_feature = cat_(sf, np.int32)
        self.threshold = cat_(th, np.float64)
        self.decision_type = cat_(dt, np.int8)
        self.left_child = cat_(lc, np.int32)
        self.right_child = cat_(rc, np.int32)
        self.leaf_value = cat_(lv, np.float64)
        self.cat_bound_offset = np.asarray(cbo, np.int32)
        self.cat_boundaries = cat_(cb, np.int32)
        self.cat_word_offset = np.asarray(cwo, np.int32)
        self.cat_words = cat_(cw, np.uint32)

    def _common_args(self):
        c = np.ctypeslib.as_ctypes
        return (self.node_offset.ctypes, self.leaf_offset.ctypes,
                self.split_feature.ctypes, self.threshold.ctypes,
                self.decision_type.ctypes, self.left_child.ctypes,
                self.right_child.ctypes, self.leaf_value.ctypes,
                self.cat_bound_offset.ctypes, self.cat_boundaries.ctypes,
                self.cat_word_offset.ctypes, self.cat_words.ctypes)

    def predict(self, X: np.ndarray, num_trees: int, num_class: int,
                early_stop_freq: int = 0,
                early_stop_margin: float = 0.0) -> Optional[np.ndarray]:
        """[k, n] summed raw scores via the native walker; None = no lib."""
        lib = native_lib()
        if lib is None:
            return None
        X = np.ascontiguousarray(X, np.float64)
        n = X.shape[0]
        out = np.zeros((num_class, n), np.float64)
        args = self._common_args()
        lib.LGBMTPU_ForestPredict(
            X.ctypes, ctypes.c_int64(n), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(num_trees), ctypes.c_int32(num_class),
            *args, ctypes.c_int32(early_stop_freq),
            ctypes.c_double(early_stop_margin), out.ctypes)
        return out

    def predict_leaf(self, X: np.ndarray,
                     num_trees: int) -> Optional[np.ndarray]:
        """[n, T] leaf indices via the native walker; None = no lib."""
        lib = native_lib()
        if lib is None:
            return None
        X = np.ascontiguousarray(X, np.float64)
        n = X.shape[0]
        out = np.zeros((n, num_trees), np.int32)
        args = self._common_args()
        lib.LGBMTPU_ForestPredictLeaf(
            X.ctypes, ctypes.c_int64(n), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(num_trees), *args, out.ctypes)
        return out


class BinnedForestTables:
    """Bin-space node tables for the native binned walker.

    The raw-value tables (ForestTables) walk double thresholds; these walk
    threshold_in_bin / split_feature_inner with the per-feature bin
    metadata, matching gbdt._predict_binned exactly.  Used by valid-score
    updates, DART drop/restore, and rollback, where trees are re-scored
    against already-binned datasets.
    """

    def __init__(self, trees: List, meta):
        no, lo, cbo, cwo = [0], [0], [0], [0]
        sf, th, dt, lc, rc, lv, cb, cw = [], [], [], [], [], [], [], []
        for t in trees:
            ni = max(t.num_leaves - 1, 0)
            no.append(no[-1] + ni)
            lo.append(lo[-1] + t.num_leaves)
            sf.append(t.split_feature_inner[:ni])
            th.append(t.threshold_in_bin[:ni])
            dt.append(t.decision_type[:ni])
            lc.append(t.left_child[:ni])
            rc.append(t.right_child[:ni])
            lv.append(t.leaf_value[:t.num_leaves])
            cb.append(np.asarray(t.cat_boundaries_inner, np.int32))
            cw.append(np.asarray(t.cat_threshold_inner, np.uint32))
            cbo.append(cbo[-1] + len(t.cat_boundaries_inner))
            cwo.append(cwo[-1] + len(t.cat_threshold_inner))

        def cat_(parts, dtype):
            return (np.ascontiguousarray(np.concatenate(parts), dtype=dtype)
                    if parts else np.zeros(0, dtype))

        self.num_trees = len(trees)
        self.node_offset = np.asarray(no, np.int32)
        self.leaf_offset = np.asarray(lo, np.int32)
        self.split_feature_inner = cat_(sf, np.int32)
        self.threshold_in_bin = cat_(th, np.int32)
        self.decision_type = cat_(dt, np.int8)
        self.left_child = cat_(lc, np.int32)
        self.right_child = cat_(rc, np.int32)
        self.leaf_value = cat_(lv, np.float64)
        self.cat_bound_offset = np.asarray(cbo, np.int32)
        self.cat_boundaries = cat_(cb, np.int32)
        self.cat_word_offset = np.asarray(cwo, np.int32)
        self.cat_words = cat_(cw, np.uint32)
        self.num_bin = np.ascontiguousarray(meta["num_bin"], np.int32)
        self.default_bin = np.ascontiguousarray(meta["default_bin"],
                                                np.int32)
        self.missing_type = np.ascontiguousarray(meta["missing_type"],
                                                 np.int32)

    def predict_subset(self, bins: np.ndarray, tree_ids, scales
                       ) -> Optional[np.ndarray]:
        """sum_i scales[i] * tree_ids[i](bins_row) per row; None = no lib
        or unsupported bin dtype."""
        lib = native_lib()
        # stale prebuilt libs may predate this symbol: fall back, don't die
        if lib is None or not hasattr(lib,
                                      "LGBMTPU_ForestPredictBinnedSubset"):
            return None
        if bins.dtype == np.uint8:
            dtype_flag = 0
        elif bins.dtype == np.uint16:
            dtype_flag = 1
        else:
            return None
        bins = np.ascontiguousarray(bins)
        tree_ids = np.ascontiguousarray(tree_ids, np.int32)
        scales = np.ascontiguousarray(scales, np.float64)
        n = bins.shape[0]
        out = np.zeros(n, np.float64)
        lib.LGBMTPU_ForestPredictBinnedSubset(
            bins.ctypes, ctypes.c_int32(dtype_flag), ctypes.c_int64(n),
            ctypes.c_int32(bins.shape[1]), tree_ids.ctypes, scales.ctypes,
            ctypes.c_int32(len(tree_ids)),
            self.node_offset.ctypes, self.leaf_offset.ctypes,
            self.split_feature_inner.ctypes, self.threshold_in_bin.ctypes,
            self.decision_type.ctypes, self.left_child.ctypes,
            self.right_child.ctypes, self.leaf_value.ctypes,
            self.cat_bound_offset.ctypes, self.cat_boundaries.ctypes,
            self.cat_word_offset.ctypes, self.cat_words.ctypes,
            self.num_bin.ctypes, self.default_bin.ctypes,
            self.missing_type.ctypes, out.ctypes)
        return out


def set_num_threads(n: int) -> None:
    """Cap the native walker's OpenMP threads (reference `num_threads`
    config); 0/negative restores the OpenMP default of all cores."""
    lib = native_lib()
    if lib is not None:
        lib.LGBMTPU_SetNumThreads(ctypes.c_int32(int(n)))
