"""Retrain policies: WHEN to retrain and HOW MUCH model to move.

Three triggers, checked in priority order once the buffer holds at
least `tpu_continual_min_rows` labeled rows:

* ``drift``    — the serving drift monitor's `psi_warn` is active
  (sampled live traffic sits at/above `serving_drift_psi_warn`);
* ``rows``     — a full retention window of rows has arrived since the
  last retrain (the model has never seen any of the buffered traffic);
* ``interval`` — `tpu_continual_interval_s` wall-clock cadence.

Each trigger maps (policy ``auto``) to the cheapest response that can
plausibly fix it:

* ``refit``    — `Booster.refit`: keep every tree's structure, re-fit
  the leaf values on the buffered window.  Cheap (no growing, no new
  compiles downstream — the candidate is same-shaped by construction);
  right for rows/cadence triggers where the relationship is stable and
  only the magnitudes moved.
* ``boost``    — K more trees via a warm `init_model` continue on the
  buffered rows, binned through the FROZEN training mappers (the
  buffer's reference shim) and GOSS-style weighted toward fresh blocks;
  right for a drift trigger where the model needs new structure.
* ``resketch`` — same warm continue, but bin finding runs FRESH over
  the buffered rows: the escalation for drift whose PSI mass sits in
  the frozen mappers' overflow/tail bins (`tail_fraction()` at/above
  `tpu_continual_resketch_tail_frac`) — the live distribution walked
  off the training range, so re-fitting inside stale bins cannot see
  it.  After a promoted resketch the controller rebuilds the ingest
  buffer from the candidate's new mappers.

Boost/resketch runs checkpoint through the PR-7 manager (dir
`<tpu_continual_dir>/retrain`): a controller killed mid-retrain resumes
the interrupted boost on restart instead of re-paying completed rounds;
the directory is cleared after a completed retrain so a FINISHED run
never masquerades as an interrupted one.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Dict, Optional, Tuple

from ..config import Config
from ..utils import faultline

TRIGGERS = ("drift", "rows", "interval")
POLICIES = ("auto", "refit", "boost", "resketch")

# num-iteration aliases engine.train lets OVERRIDE its argument; a base
# model's params carrying one would silently replace boost-K with a
# full-length retrain
_NUM_ITER_ALIASES = ("num_boost_round", "num_iterations", "num_iteration",
                     "n_iter", "num_tree", "num_trees", "num_round",
                     "num_rounds", "n_estimators")


class ContinualTrainer:
    """Policy engine + retrain launcher over one RowBuffer."""

    def __init__(self, buffer, config: Optional[Config] = None,
                 params: Optional[Dict] = None):
        self.buffer = buffer
        self.cfg = config if config is not None else Config({})
        if str(self.cfg.tpu_continual_policy) not in POLICIES:
            raise ValueError(
                f"tpu_continual_policy must be one of {POLICIES}, got "
                f"{self.cfg.tpu_continual_policy!r}")
        # extra training params for the boost paths (layered over the
        # base model's own params)
        self.params = dict(params or {})
        self._rows_at_last = 0
        self._last_retrain_t = time.monotonic()

    # -- triggers ------------------------------------------------------
    def pending_trigger(self, drift_warn: bool) -> Optional[str]:
        """Highest-priority trigger currently firing, or None."""
        if self.buffer.rows < int(self.cfg.tpu_continual_min_rows):
            return None
        if drift_warn:
            return "drift"
        rows_since = self.buffer.ingested_total - self._rows_at_last
        if rows_since >= self.buffer.retain_rows:
            return "rows"
        interval = float(self.cfg.tpu_continual_interval_s)
        if interval > 0 and \
                time.monotonic() - self._last_retrain_t >= interval:
            return "interval"
        return None

    def choose_policy(self, trigger: str) -> str:
        pinned = str(self.cfg.tpu_continual_policy)
        if pinned != "auto":
            return pinned
        if trigger == "drift":
            tail = self.buffer.tail_fraction()
            if tail >= float(self.cfg.tpu_continual_resketch_tail_frac):
                return "resketch"
            return "boost"
        return "refit"

    # -- retrain -------------------------------------------------------
    def retrain(self, base, trigger: str) -> Tuple[object, str]:
        """Produce a candidate Booster from `base` + the buffered
        window; returns (candidate, policy-used).  Raises ValueError
        when the window carries no labels (every retrain path is
        supervised) — the controller folds that into a deferral."""
        policy = self.choose_policy(trigger)
        X, y, w = self.buffer.raw(
            float(self.cfg.tpu_continual_fresh_decay))
        if y is None or X.shape[0] == 0:
            raise ValueError(
                "buffered window has no labels; every retrain path is "
                "supervised — ingest labeled rows (delayed-label joins "
                "happen upstream of observe())")
        faultline.fire("continual_retrain", trigger=trigger,
                       policy=policy, rows=int(X.shape[0]))
        if policy == "refit":
            cand = base.refit(
                X, y,
                decay_rate=float(self.cfg.tpu_continual_refit_decay))
        else:
            cand = self._boost(base, X, y, w, frozen=(policy == "boost"))
        self._rows_at_last = self.buffer.ingested_total
        self._last_retrain_t = time.monotonic()
        return cand, policy

    @staticmethod
    def _base_params(base) -> Dict:
        """Training params reusable from the base model.  A booster
        loaded from a model FILE carries its objective in model-string
        form ('binary sigmoid:1') plus metadata keys that are not
        training params — normalize both so a warm continue from a
        loaded model trains under the objective it was saved with."""
        params = dict(getattr(base, "params", None) or {})
        params.pop("feature_infos", None)
        obj = str(params.get("objective", "") or "")
        if " " in obj:
            toks = obj.split()
            params["objective"] = toks[0]
            for t in toks[1:]:
                if ":" in t:
                    k, v = t.split(":", 1)
                    params.setdefault(k, v)
        return params

    def _boost(self, base, X, y, w, frozen: bool):
        """K-more-trees warm continue (engine.train init_model merge)."""
        from .. import engine
        from ..basic import Dataset

        params = self._base_params(base)
        params.update(self.params)
        for alias in _NUM_ITER_ALIASES:
            params.pop(alias, None)
        ckpt_dir = self._checkpoint_dir()
        resume = False
        if ckpt_dir:
            params["tpu_checkpoint_dir"] = ckpt_dir
            resume = os.path.isdir(ckpt_dir) and any(
                os.scandir(ckpt_dir))
        ds = Dataset(X, label=y, weight=w, params=params)
        if frozen:
            # bin the window through the model's FROZEN training
            # mappers (the buffer's shim is a mapper-only reference):
            # structure learned by the continue lines up bin-for-bin
            # with what incremental ingest accumulated
            ref = Dataset(None, params=params)
            ref._inner = self.buffer.reference_data()
            ds.reference = ref
        cand = engine.train(
            params, ds,
            num_boost_round=int(self.cfg.tpu_continual_boost_rounds),
            init_model=base, verbose_eval=False, resume=resume)
        if ckpt_dir:
            # a COMPLETED retrain must not leave checkpoints for the
            # next one to "resume" from
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        return cand

    def _checkpoint_dir(self) -> str:
        root = str(self.cfg.tpu_continual_dir or "")
        return os.path.join(root, "retrain") if root else ""
