"""The train-behind-serve loop: one controller per served model name.

`observe(X, y)` is the traffic mirror — every labeled batch lands in
the ingest buffer.  `step()` is one control cycle: scrape drift, check
triggers, retrain, shadow-gate, promote (or defer/refuse), and watch a
fresh promotion for regressions.  `run()` loops `step()` on the
`tpu_continual_poll_s` cadence until stopped.

Failure containment is the controller's core contract: a collective
timeout inside a retrain, a device OOM during the candidate load, a
refused shadow, an injected fault at any `continual_*` faultline point
— each ends THAT cycle (counted in `lgbm_continual_deferred_total` or
the refusal counter, flight-recorded) and the loop lives; accepted
serving requests never see an error from the train-behind side.

Metrics (process-global obs registry, so they ride the serving
session's `/metrics` scrape):

* `lgbm_continual_retrains_total{trigger,policy}` — retrains fired
* `lgbm_continual_promotions_total` / `_refusals_total` /
  `_rollbacks_total` / `_deferred_total{reason}`
* `lgbm_continual_buffer_rows` / `_bytes` — ingest window (buffer.py)
* `lgbm_continual_swap_seconds` — alias-flip gap histogram
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..config import Config
from ..utils import faultline, membudget
from .buffer import RowBuffer
from .promote import promote_candidate, rollback
from .trainer import ContinualTrainer

# alias-flip gap: a dict write under the registry lock — single-digit
# microseconds healthy, milliseconds means lock contention
_SWAP_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0)

# how many post-promote cycles a fresh candidate stays on watch:
# breaker-open or re-warned drift inside the window auto-rolls back
_WATCH_STEPS = 3


class ContinualController:
    """Drift-triggered retrain + shadow-gated promotion for one model."""

    def __init__(self, session, name: str,
                 config: Optional[Config] = None,
                 params: Optional[Dict] = None):
        self.session = session
        self.registry = session.registry
        self.name = str(name)
        self.cfg = config if config is not None else session.config
        live = self.registry.resolve(self.name)   # must already serve
        self.buffer = RowBuffer(live.booster, self.cfg)
        self.trainer = ContinualTrainer(self.buffer, self.cfg, params)
        self._lock = threading.Lock()
        # guarded by _lock (graftlint C301): post-promote watch state
        self._watch: Optional[Dict] = None
        self._stop = threading.Event()

    # -- ingest (the traffic mirror) -----------------------------------
    def observe(self, X, y=None) -> int:
        """Mirror one batch of live traffic (with labels when the join
        has them) into the retrain window."""
        return self.buffer.ingest(X, y)

    # -- one control cycle ---------------------------------------------
    def step(self) -> Dict:
        """Run one cycle; returns a status dict (`status` in idle /
        retrained+promoted / refused / deferred / rolled_back /
        watching).  NEVER raises: every failure mode folds into a
        counted, flight-recorded deferral so the loop survives."""
        try:
            return self._step_inner()
        except Exception as exc:  # noqa: BLE001 — containment boundary
            self._count_deferred(type(exc).__name__)
            from ..obs import flightrecorder

            flightrecorder.note("continual", "cycle_error",
                                model=self.name,
                                error=f"{type(exc).__name__}: "
                                      f"{str(exc)[:200]}")
            return {"status": "deferred", "reason": str(exc)}

    def _step_inner(self) -> Dict:
        rolled = self._watch_promoted()
        if rolled is not None:
            return rolled
        warn = self._drift_warn_active()
        trigger = self.trainer.pending_trigger(warn)
        if trigger is None:
            return {"status": "idle", "drift_warn": warn,
                    "buffer_rows": self.buffer.rows}
        live = self.registry.resolve(self.name)
        try:
            cand, policy = self.trainer.retrain(live.booster, trigger)
        except (ValueError, membudget.ServingMemoryExhausted,
                faultline.FaultInjected) as exc:
            self._count_deferred("retrain_failed")
            return {"status": "deferred", "trigger": trigger,
                    "reason": str(exc)}
        except Exception as exc:
            # collective timeout, device loss, ... — the retrain side
            # died; serving never noticed
            self._count_deferred(type(exc).__name__)
            return {"status": "deferred", "trigger": trigger,
                    "reason": str(exc)}
        self._metric("lgbm_continual_retrains_total", trigger=trigger,
                     policy=policy,
                     help="continual retrains fired, by trigger and "
                          "retrain policy")
        Xs, ys = self._shadow_sample()
        res = promote_candidate(self.registry, self.name, cand, Xs, ys,
                                tolerance=float(
                                    self.cfg.tpu_continual_tolerance))
        out = {"status": res["status"], "trigger": trigger,
               "policy": policy}
        if res["status"] == "deferred":
            self._count_deferred("candidate_load")
            out["reason"] = res.get("reason", "")
        elif res["status"] == "refused":
            self._metric("lgbm_continual_refusals_total",
                         help="shadow-gate refusals (candidate scored "
                              "worse than live)")
            out["verdict"] = res["verdict"]
        else:  # promoted
            self._metric("lgbm_continual_promotions_total",
                         help="shadow-gated promotions (bare-name alias "
                              "flips)")
            from ..obs import REGISTRY

            REGISTRY.observe("lgbm_continual_swap_seconds",
                             float(res["swap_seconds"]),
                             buckets=_SWAP_BUCKETS)
            with self._lock:
                self._watch = {"prev_key": res["prev_key"],
                               "shadow_key": res["shadow_key"],
                               "steps": _WATCH_STEPS}
            if policy == "resketch":
                # the promoted model carries FRESH mappers; rebuild the
                # ingest window so it bins through them (the old window
                # described the old binning)
                promoted = self.registry.resolve(self.name)
                self.buffer = RowBuffer(promoted.booster, self.cfg)
                self.trainer.buffer = self.buffer
            out.update(verdict=res["verdict"],
                       swap_seconds=res["swap_seconds"])
        return out

    # -- internals -----------------------------------------------------
    def _drift_warn_active(self) -> bool:
        """Scrape-then-poll: `session.drift()` absorbs pending sampled
        traffic (the dispatch tap only stashes), then the live entry's
        monitor answers whether PSI sits at/above the warn line."""
        self.session.drift()
        try:
            entry = self.registry.resolve(self.name)
        except KeyError:
            return False
        mon = getattr(entry, "drift", None)
        return bool(mon is not None and mon.warn_active())

    def _watch_promoted(self) -> Optional[Dict]:
        """Post-promote regression watch: a just-promoted candidate
        whose breaker opens or whose drift re-warns inside the watch
        window rolls back to the displaced version."""
        with self._lock:
            watch = self._watch
        if watch is None:
            return None
        try:
            entry = self.registry.resolve(self.name)
        except KeyError:
            with self._lock:
                self._watch = None
            return None
        if entry.key != watch["shadow_key"]:
            # operator moved the alias themselves; stand down
            with self._lock:
                self._watch = None
            return None
        reason = None
        if not entry.healthy:
            reason = "breaker_open"
        else:
            self.session.drift()
            mon = getattr(entry, "drift", None)
            if mon is not None and mon.warn_active():
                reason = "drift_regression"
        if reason is None:
            with self._lock:
                watch["steps"] -= 1
                if watch["steps"] <= 0:
                    self._watch = None
            return None
        rollback(self.registry, self.name, watch["prev_key"],
                 watch["shadow_key"], reason)
        self._metric("lgbm_continual_rollbacks_total",
                     help="post-promote auto-rollbacks (breaker open or "
                          "drift regression inside the watch window)")
        with self._lock:
            self._watch = None
        return {"status": "rolled_back", "reason": reason}

    def _shadow_sample(self):
        """Newest buffered rows (mirrored live traffic) as the shadow
        scoring sample — the candidate is judged on what traffic looks
        like NOW."""
        X, y, _w = self.buffer.raw()
        n = max(int(self.cfg.tpu_continual_shadow_rows), 1)
        if X.shape[0] > n:
            X = X[-n:]
            y = y[-n:] if y is not None else None
        return X, y

    def _count_deferred(self, reason: str) -> None:
        self._metric("lgbm_continual_deferred_total", reason=reason,
                     help="continual cycles that ended without a "
                          "promotion attempt completing, by reason")

    def _metric(self, name: str, help: str = "", **labels) -> None:
        from ..obs import REGISTRY

        REGISTRY.inc(name, 1, help=help, **labels)

    # -- the long-running loop -----------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def run(self, max_cycles: Optional[int] = None) -> int:
        """Loop `step()` on the poll cadence until `stop()` (or
        `max_cycles`); returns cycles run."""
        poll = max(float(self.cfg.tpu_continual_poll_s), 0.05)
        cycles = 0
        while not self._stop.is_set():
            self.step()
            cycles += 1
            if max_cycles is not None and cycles >= max_cycles:
                break
            self._stop.wait(poll)
        return cycles
