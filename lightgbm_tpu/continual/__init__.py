"""Continual learning (ISSUE 17): the drift-triggered train-behind-serve
loop that closes live traffic back into training and out to serving with
zero downtime.

Four pieces compose substrate shipped by earlier PRs:

* `buffer`   — incremental ingest: streaming rows bin through the FROZEN
  training mappers (PR-3 chunked ingest kernel) into PR-16 `[G, rows]`
  host blocks with a bounded retention window.
* `trainer`  — retrain policies: leaf refit vs boost-K-more-trees (warm
  `init_model` continue), fired by psi_warn / row-count / cadence
  triggers, checkpointed through the PR-7 manager.
* `promote`  — shadow-gated promotion: candidate loads under a shadow
  name (PR-15 budget preflight or DEFER), `shadow_verdict()` scores it
  on mirrored traffic, the bare-name alias swaps atomically, and a
  refuse/breaker/drift regression auto-rolls back.
* `controller` — the long-running driver `python -m lightgbm_tpu
  continual` wires to a serving session, with `lgbm_continual_*`
  metrics and faultline points at every stage boundary.
"""

from .buffer import RowBuffer
from .controller import ContinualController
from .promote import promote_candidate, shadow_verdict
from .trainer import ContinualTrainer

__all__ = ["RowBuffer", "ContinualTrainer", "ContinualController",
           "promote_candidate", "shadow_verdict"]
