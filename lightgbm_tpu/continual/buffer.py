"""Incremental ingest buffer: streaming rows -> binned row blocks.

Rows arriving from live traffic accumulate through the FROZEN training
bin mappers (the model's ``tpu_bin_mappers:`` snapshot) via the PR-3
chunked ingest kernel (`ops/binning.py DeviceBinner`), falling back to
host per-column binning when the kernel declines the mapper set.  Each
ingest lands one transposed C-contiguous ``[G, rows]`` block — the PR-16
out-of-core block layout — so `host_blocks()` feeds the stream grower
(or any block consumer) without a relayout.

The buffer is a bounded SLIDING WINDOW (`tpu_continual_buffer_rows`):
oldest blocks evict as new ones land, so a long-running controller's
memory is flat regardless of stream length.  Raw rows + labels ride
beside the bins because both retrain paths consume raw values (leaf
refit re-predicts leaves; a boost-K Dataset re-bins through a reference
or re-sketches).

Re-sketch escalation: binning through frozen mappers saturates when the
live distribution walks off the training range — drifted values pile
into each feature's overflow/tail bin.  `tail_fraction()` tracks the
worst per-feature fraction of buffered rows landing in the last bin;
the policy engine escalates a drift-triggered retrain to a full
re-sketch when it crosses `tpu_continual_resketch_tail_frac` (high PSI
concentrated in tail bins means the MAPPERS are stale, not just the
occupancy).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils import faultline, lockcheck


class _Block:
    """One ingested block: binned [G, rows] + the raw rows behind it."""

    __slots__ = ("bins_t", "X", "y", "tail", "seq")

    def __init__(self, bins_t: np.ndarray, X: np.ndarray,
                 y: Optional[np.ndarray], tail: np.ndarray, seq: int):
        self.bins_t = bins_t    # [G, rows] C-contiguous (PR-16 layout)
        self.X = X              # [rows, F] raw f64
        self.y = y              # [rows] labels (None = unlabeled)
        self.tail = tail        # [G] rows landing in each feature's last bin
        self.seq = seq

    @property
    def rows(self) -> int:
        return int(self.X.shape[0])

    @property
    def nbytes(self) -> int:
        return int(self.bins_t.nbytes + self.X.nbytes
                   + (self.y.nbytes if self.y is not None else 0))


class RowBuffer:
    """Bounded binned-row window behind one served model.

    Thread-safe: `ingest` may run on a traffic-mirroring thread while
    the retrain side reads `raw()`/`host_blocks()` — all mutable state
    is guarded by `_lock` (graftlint C301 OWNERSHIP).  The expensive
    work (binning) runs OUTSIDE the lock; only list/counter updates
    hold it.
    """

    def __init__(self, booster, config: Optional[Config] = None):
        cfg = config if config is not None else Config({})
        drv = booster._driver
        drv._materialize()
        ctx = drv._pred_context()
        if ctx is None:
            raise ValueError(
                "continual buffer needs the model's bin-mapper snapshot "
                "(tpu_bin_mappers: trailer) — the FROZEN training "
                "binning is what incremental ingest bins through")
        self._mappers = ctx.mappers
        self._used = [int(c) for c in ctx.used_feature_idx]
        self.num_feature = int(booster.num_feature())
        max_bin = max((self._mappers[c].num_bin for c in self._used),
                      default=2)
        self._dtype = np.uint8 if max_bin <= 256 else np.uint16
        from ..ops.binning import DeviceBinner

        # PR-3 chunked ingest kernel; None (huge categorical LUTs) falls
        # back to exact host per-column binning — same bins either way
        self._binner = DeviceBinner.build(
            self._mappers, self._used, self._dtype,
            int(cfg.tpu_ingest_chunk_rows))
        self.retain_rows = max(int(cfg.tpu_continual_buffer_rows), 1)
        self._lock = lockcheck.make_lock("continual.buffer")
        # guarded by _lock:
        self._blocks: List[_Block] = []
        self._rows = 0
        self._seq = 0
        self._ingested_total = 0
        self._evicted_total = 0

    # -- ingest --------------------------------------------------------
    def ingest(self, X, y=None) -> int:
        """Bin + buffer one batch of streaming rows; returns the rows
        accepted.  Oldest blocks evict past the retention window."""
        X = np.ascontiguousarray(np.atleast_2d(
            np.asarray(X, np.float64)))
        if X.shape[0] == 0:
            return 0
        if X.shape[1] != self.num_feature:
            raise ValueError(
                f"ingest row width {X.shape[1]} != model feature count "
                f"{self.num_feature}")
        yv = None
        if y is not None:
            yv = np.asarray(y, np.float64).ravel()
            if yv.size != X.shape[0]:
                raise ValueError(
                    f"{yv.size} labels for {X.shape[0]} rows")
        faultline.fire("continual_ingest", rows=int(X.shape[0]))
        bins = self._bin(X)                       # [rows, G]
        bins_t = np.ascontiguousarray(bins.T)     # [G, rows] block layout
        tail = np.empty(len(self._used), np.int64)
        for j, c in enumerate(self._used):
            tail[j] = int((bins[:, j] ==
                           self._mappers[c].num_bin - 1).sum())
        with self._lock:
            self._seq += 1
            self._blocks.append(_Block(bins_t, X, yv, tail, self._seq))
            self._rows += int(X.shape[0])
            self._ingested_total += int(X.shape[0])
            while self._rows > self.retain_rows and len(self._blocks) > 1:
                gone = self._blocks.pop(0)
                self._rows -= gone.rows
                self._evicted_total += gone.rows
        self._publish()
        return int(X.shape[0])

    def _bin(self, X: np.ndarray) -> np.ndarray:
        if self._binner is not None:
            return np.asarray(self._binner.bin_matrix(X))
        out = np.empty((X.shape[0], len(self._used)), self._dtype)
        for j, c in enumerate(self._used):
            out[:, j] = self._mappers[c].values_to_bins(
                np.ascontiguousarray(X[:, c])).astype(self._dtype,
                                                      copy=False)
        return out

    def _publish(self) -> None:
        from ..obs import REGISTRY

        with self._lock:
            rows, nbytes = self._rows, sum(b.nbytes for b in self._blocks)
        REGISTRY.set_gauge("lgbm_continual_buffer_rows", rows,
                           help="rows resident in the continual ingest "
                                "buffer (bounded retention window)")
        REGISTRY.set_gauge("lgbm_continual_buffer_bytes", nbytes,
                           help="host bytes (bins + raw rows + labels) "
                                "of the continual ingest buffer")

    # -- reads ---------------------------------------------------------
    @property
    def rows(self) -> int:
        with self._lock:
            return self._rows

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(b.nbytes for b in self._blocks)

    @property
    def ingested_total(self) -> int:
        """Monotone rows-ever-ingested counter (the row-count trigger
        diffs it; window eviction never rewinds it)."""
        with self._lock:
            return self._ingested_total

    def tail_fraction(self) -> float:
        """Worst per-feature fraction of buffered rows sitting in that
        feature's overflow/tail bin — the re-sketch escalation signal
        (drifted values saturate the frozen mappers' last bins)."""
        with self._lock:
            if not self._blocks or self._rows == 0:
                return 0.0
            tails = np.sum([b.tail for b in self._blocks], axis=0)
            rows = self._rows
        return float(tails.max()) / float(rows) if tails.size else 0.0

    def host_blocks(self, stream_rows: Optional[int] = None
                    ) -> List[np.ndarray]:
        """Buffered bins as C-contiguous [G, rows] blocks (the PR-16
        out-of-core unit).  Default: one block per ingest batch; pass
        `stream_rows` to re-partition into stream-grower-width blocks
        (ops/stream.make_host_blocks semantics)."""
        with self._lock:
            blocks = [b.bins_t for b in self._blocks]
        if stream_rows is None or not blocks:
            return blocks
        from ..ops.stream import make_host_blocks

        bins_t = blocks[0] if len(blocks) == 1 else \
            np.concatenate(blocks, axis=1)
        return make_host_blocks(bins_t, int(stream_rows))

    def raw(self, fresh_decay: float = 1.0
            ) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
        """(X, y, weight) across the window, newest-last.  `weight` is
        the GOSS-style freshness weighting: the newest block weighs 1.0
        and each older block decays by `fresh_decay` — incremental
        rounds lean toward fresh traffic without discarding the tail
        (the small-gradient analog of GOSS's amplified 'other' sample).
        y is None when ANY buffered block arrived unlabeled."""
        with self._lock:
            blocks = list(self._blocks)
        if not blocks:
            return (np.zeros((0, self.num_feature)), None, np.zeros(0))
        X = np.concatenate([b.X for b in blocks], axis=0)
        y = None
        if all(b.y is not None for b in blocks):
            y = np.concatenate([b.y for b in blocks])
        decay = min(max(float(fresh_decay), 0.0), 1.0)
        ages = range(len(blocks) - 1, -1, -1)   # oldest first -> max age
        w = np.concatenate([
            np.full(b.rows, decay ** age, np.float64)
            for b, age in zip(blocks, ages)])
        return X, y, w

    def reference_data(self) -> object:
        """A mapper-only `TrainingData` shim usable as a Dataset
        binning reference: a boost-K continue built against it bins its
        rows through the SAME frozen mappers this buffer ingests
        through (`_adopt_reference_mappers` reads exactly these
        fields)."""
        from ..io.dataset import TrainingData

        ref = TrainingData()
        ref.mappers = self._mappers
        ref.used_feature_idx = list(self._used)
        ref.num_total_features = self.num_feature
        return ref

    def drain(self) -> int:
        """Drop every buffered block (after a successful re-sketch the
        old window described the OLD binning); returns rows dropped."""
        with self._lock:
            dropped = self._rows
            self._blocks = []
            self._rows = 0
        self._publish()
        return dropped
