"""Shadow-gated zero-downtime promotion.

A retrained candidate never serves blind: it loads into the registry
under a SHADOW name (`<name>.shadow`) beside the live model — the PR-15
HBM planner must clear the joint residency first, or the attempt is
DEFERRED rather than OOM-crashed — then `shadow_verdict()` scores both
models on the same mirrored live sample.  Only a promote verdict flips
the bare-name alias (`ModelRegistry.promote`, one dict write under the
registry lock), so in-flight requests finish on whichever entry they
resolved and no request is dropped or double-answered.  A refuse, an
open breaker, or a post-promote drift regression rolls the alias back
the same way and flight-records the event.

`shadow_verdict` is the SINGLE implementation of the promotion gate:
`tools/model_report.py --shadow` (the operator CLI) and the continual
controller both import it, so the offline verdict and the automated one
can never disagree.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..utils import faultline, membudget


# ---------------------------------------------------------------------------
# the verdict (shared with tools/model_report.py --shadow)
# ---------------------------------------------------------------------------
def _loss(booster, X: np.ndarray, y: np.ndarray) -> Tuple[str, float]:
    """(metric name, loss) — binary logloss for binary objectives,
    mean squared error otherwise.  Lower is better for both."""
    obj = str(booster._driver.loaded_params.get(
        "objective", "") or (booster._driver.objective.to_model_string()
                             if booster._driver.objective else ""))
    pred = np.asarray(booster.predict(X), np.float64)
    if obj.startswith("binary"):
        p = np.clip(pred, 1e-15, 1.0 - 1e-15)
        return "binary_logloss", float(
            -np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))
    if pred.ndim > 1:  # multiclass: negative log-likelihood of y class
        p = np.clip(pred[np.arange(len(y)), y.astype(int)], 1e-15, 1.0)
        return "multi_logloss", float(-np.mean(np.log(p)))
    return "l2", float(np.mean((pred - y) ** 2))


def shadow_verdict(live, candidate, X: np.ndarray,
                   y: Optional[np.ndarray] = None,
                   tolerance: float = 0.0) -> Dict:
    """Score candidate vs live on the same sample.  Returns the
    prediction-delta distribution and — with labels — the promote/
    refuse verdict: promote iff candidate_loss <= live_loss *
    (1 + tolerance)."""
    X = np.ascontiguousarray(np.atleast_2d(np.asarray(X, np.float64)))
    pl = np.asarray(live.predict(X, raw_score=True), np.float64)
    pc = np.asarray(candidate.predict(X, raw_score=True), np.float64)
    delta = np.abs(pc - pl).ravel()
    out: Dict = {
        "rows": int(X.shape[0]),
        "delta": {
            "mean": float(delta.mean()) if delta.size else 0.0,
            "p50": float(np.percentile(delta, 50)) if delta.size else 0.0,
            "p95": float(np.percentile(delta, 95)) if delta.size else 0.0,
            "max": float(delta.max()) if delta.size else 0.0,
        },
    }
    if y is None:
        out["verdict"] = "no-labels"
        out["reason"] = ("sample carries no labels; delta distribution "
                         "only — pass labeled data for a promote/refuse "
                         "verdict")
        return out
    y = np.asarray(y, np.float64).ravel()
    metric, live_loss = _loss(live, X, y)
    _, cand_loss = _loss(candidate, X, y)
    out["metric"] = metric
    out["live_loss"] = live_loss
    out["candidate_loss"] = cand_loss
    out["tolerance"] = float(tolerance)
    promote = (math.isfinite(cand_loss)
               and cand_loss <= live_loss * (1.0 + float(tolerance)))
    out["verdict"] = "promote" if promote else "refuse"
    out["reason"] = (
        f"candidate {metric} {cand_loss:.6g} vs live {live_loss:.6g} "
        f"(tolerance {tolerance:g})")
    return out


# ---------------------------------------------------------------------------
# the promotion pipeline
# ---------------------------------------------------------------------------
def shadow_name(name: str) -> str:
    return f"{name}.shadow"


def promote_candidate(registry, name: str, candidate,
                      X: np.ndarray, y: Optional[np.ndarray],
                      tolerance: float = 0.0) -> Dict:
    """Run one candidate through the full shadow gate.

    Returns a status dict; `status` is one of

    * ``deferred``  — the PR-15 planner could not clear candidate+live
      joint residency (cold-model eviction included); nothing touched
      the device, the controller retries next cycle.
    * ``refused``   — the candidate loaded and scored worse than the
      live model on the mirrored sample; it was unloaded again.  The
      verdict dict rides along.
    * ``promoted``  — the bare-name alias now points at the candidate.
      `prev_key` (the displaced live key, possibly None) and
      `shadow_key` ride along so the caller can `rollback()`;
      `swap_seconds` is the measured alias-flip gap.

    The load itself is the only stage that can raise past the DEFER
    preflight (e.g. a real device OOM mid-upload after the plan
    cleared) — `ServingMemoryExhausted` from it is also folded into
    ``deferred`` so a transient squeeze never kills the loop.
    """
    from ..obs import flightrecorder

    faultline.fire("continual_shadow_load", model=name)
    # DEFER preflight: same PR-15 plan + admission formula the registry
    # applies, but WITHOUT burning the upload/warmup when it cannot fit
    # even after shedding cold third models (the live alias is never an
    # eviction victim)
    plan = membudget.plan_model_load(candidate, registry.config)
    if plan is not None:
        tables = plan.components.get("packed_tables", 0)
        scratch = plan.components.get("launch_scratch", 0)
        headroom = registry.admission_headroom(tables, scratch)
        if headroom is not None and headroom < 0:
            registry.relieve_pressure(need_bytes=-headroom)
            headroom = registry.admission_headroom(tables, scratch)
        if headroom is not None and headroom < 0:
            flightrecorder.note("continual", "promotion_deferred",
                                model=name, predicted=plan.total,
                                headroom=headroom)
            return {"status": "deferred",
                    "reason": f"candidate needs {tables:,d} device bytes "
                              f"but the serving budget is "
                              f"{-headroom:,d} bytes short of joint "
                              "candidate+live residency"}
    sname = shadow_name(name)
    try:
        entry = registry.load(sname, booster=candidate)
    except membudget.ServingMemoryExhausted as exc:
        flightrecorder.note("continual", "promotion_deferred",
                            model=name, error=str(exc)[:200])
        return {"status": "deferred", "reason": str(exc)}
    live = registry.resolve(name)
    verdict = shadow_verdict(live.booster, entry.booster, X, y,
                             tolerance=tolerance)
    if verdict["verdict"] != "promote":
        # exact key, not the bare shadow name: after an earlier
        # cross-name promotion the LIVE alias points at a previous
        # `<name>.shadow@k` entry, and a bare-name unload would evict
        # every resident version of the shadow name — live included
        registry.unload(entry.key)
        flightrecorder.note("continual", "promotion_refused", model=name,
                            reason=verdict.get("reason", ""))
        return {"status": "refused", "verdict": verdict}
    faultline.fire("continual_promote", model=name)
    t0 = time.perf_counter()
    prev_key = registry.promote(name, entry.key)
    swap = time.perf_counter() - t0
    flightrecorder.note("continual", "promoted", model=name,
                        key=entry.key, prev=prev_key,
                        swap_seconds=round(swap, 6))
    return {"status": "promoted", "verdict": verdict,
            "shadow_key": entry.key, "prev_key": prev_key,
            "swap_seconds": swap}


def rollback(registry, name: str, prev_key: Optional[str],
             shadow_key: str, reason: str) -> None:
    """Undo a promotion: re-alias `name` to the displaced live key and
    drop the candidate.  Flight-recorded with the triggering reason
    (breaker open, drift regression, operator)."""
    from ..obs import flightrecorder

    if prev_key is not None:
        registry.promote(name, prev_key)
    try:
        registry.unload(shadow_key if "@" in shadow_key else
                        shadow_name(name))
    except KeyError:
        pass  # already evicted under pressure — the alias flip stands
    flightrecorder.note("continual", "rolled_back", model=name,
                        candidate=shadow_key, restored=prev_key,
                        reason=reason)
