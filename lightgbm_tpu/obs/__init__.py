"""Unified telemetry: the metrics registry + structured span tracer.

One import surface for every instrumented layer::

    from ..obs import REGISTRY, span, timed, metrics_on, tracing_on

* `REGISTRY` — process-global `MetricsRegistry` (counters, gauges,
  fixed-bucket histograms; Prometheus text export).
* `span(name, **tags)` — nested structured span (Chrome-trace/Perfetto
  export, JSONL stream, jax TraceAnnotation mirror); null when
  ``tpu_telemetry`` != trace.
* `timed(name)` — registry-backed stopwatch (the bench's segment timer).
* `configure` / `configure_from_config` — process-global policy from
  ``tpu_telemetry`` (off | metrics | trace) and ``tpu_trace_dir``.

See `obs.metrics` and `obs.trace` for the full contracts.
"""

from .metrics import (DEFAULT_SECONDS_BUCKETS, MetricsRegistry,  # noqa: F401
                      REGISTRY, histogram_quantile)
from .trace import (chrome_trace, configure, configure_from_config,  # noqa: F401
                    event, events, flush, metrics_on, mode,
                    reset_events, span, timed, trace_dir, tracing_on,
                    write_chrome_trace)
