"""Unified telemetry: metrics registry + span tracer + resource
accounting + flight recorder.

One import surface for every instrumented layer::

    from ..obs import REGISTRY, span, timed, metrics_on, tracing_on
    from ..obs import flightrecorder, resources

* `REGISTRY` — process-global `MetricsRegistry` (counters, gauges,
  fixed-bucket histograms; Prometheus text export).
* `span(name, **tags)` — nested structured span (Chrome-trace/Perfetto
  export, JSONL stream, jax TraceAnnotation mirror); null when
  ``tpu_telemetry`` != trace.
* `timed(name)` — registry-backed stopwatch (the bench's segment timer).
* `configure` / `configure_from_config` — process-global policy from
  ``tpu_telemetry`` (off | metrics | trace), ``tpu_trace_dir`` and the
  ``tpu_obs_*`` params (histogram sample ring, flight-recorder depth
  and blackbox dump dir).
* `resources` — device HBM gauges, phase-tagged peak watermarks,
  process runtime stats (ISSUE 12).
* `flightrecorder` — the ALWAYS-ON bounded ring of recent spans/
  transitions dumped to ``blackbox-host<k>.json`` on crash/hang/
  SIGTERM (ISSUE 12).
* `modelhealth` — training reference profiles
  (``tpu_feature_profile:`` trailer) + the serving drift monitor:
  PSI / Jensen-Shannon over the binned representation (ISSUE 14).

See `obs.metrics`, `obs.trace`, `obs.resources` and
`obs.flightrecorder` for the full contracts.
"""

from . import flightrecorder, modelhealth, resources  # noqa: F401
from .metrics import (DEFAULT_SECONDS_BUCKETS, MetricsRegistry,  # noqa: F401
                      REGISTRY, histogram_quantile)
from .trace import (chrome_trace, configure, configure_from_config,  # noqa: F401
                    event, events, flush, metrics_on, mode,
                    reset_events, span, timed, trace_dir, tracing_on,
                    write_chrome_trace)
