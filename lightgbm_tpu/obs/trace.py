"""Structured span tracer: nested host-side spans with monotonic walls,
Chrome-trace-event export (Perfetto-loadable), a per-host JSONL event
stream, and mirroring into jax profiler annotations.

Three telemetry modes, process-global (`configure`, wired from the
``tpu_telemetry`` / ``tpu_trace_dir`` params at learner/dataset/serving
init, or the LIGHTGBM_TPU_TELEMETRY / LIGHTGBM_TPU_TRACE_DIR env vars):

* ``off``     — default.  Every instrumentation site degenerates to one
  module-flag check; `span()` returns a shared null context manager
  (no generator, no allocation beyond the kwargs dict) so a
  100-iteration train regresses < 1% vs. the registry not existing at
  all (asserted by tests/test_telemetry.py).
* ``metrics`` — phase walls and counters flow into `obs.metrics.REGISTRY`
  but no spans are buffered.
* ``trace``   — additionally records nested spans (thread-local stack,
  thread/host/iteration tags), streams them as JSONL lines under
  ``tpu_trace_dir`` (``events-host<k>.jsonl``; incremental, so a dead
  run keeps everything up to the death), and mirrors each span into
  ``jax.profiler.TraceAnnotation`` so the SAME names appear inside
  xprof device traces.  `write_chrome_trace()` dumps the buffered spans
  as Chrome trace-event JSON (``trace-host<k>.json``) that loads
  directly in Perfetto; `tools/trace_merge.py` merges the per-host
  JSONL streams of a multihost run into one such file.

Telemetry NEVER touches PRNG streams or device values: model files are
bit-identical with tracing on or off.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from .metrics import REGISTRY

MODES = ("off", "metrics", "trace")

# module-level fast flags: the ONLY thing hot sites read when telemetry
# is off
_METRICS = False
_TRACE = False

_state_lock = threading.Lock()
_mode = "off"
_trace_dir = ""

# span buffer (Chrome export source); bounded so week-long runs cannot
# grow memory — drops are counted, never silent
_EVENT_CAP = 500_000
_events: List[Dict] = []
_events_lock = threading.Lock()
_dropped = 0

_tls = threading.local()

# perf_counter origin: every ts is µs since process telemetry start so
# Chrome/Perfetto timelines start near zero
_T0_NS = time.perf_counter_ns()

_stream_lock = threading.Lock()
_stream = None          # open JSONL file handle
_stream_path = ""

_NULL = contextlib.nullcontext()

_ANNOTATION = None      # cached jax.profiler.TraceAnnotation class


def _host_index() -> int:
    # lazy: the fault harness owns host-identity resolution (explicit
    # override > env > initialized jax backend > 0) and must never be
    # import-cycled or force backend init
    from ..utils import faultline

    return faultline.host_index()


def _annotation_cls():
    """jax.profiler.TraceAnnotation when jax is ALREADY imported (the
    tracer must never force a backend/module import), else None."""
    global _ANNOTATION
    if _ANNOTATION is not None:
        return _ANNOTATION
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        _ANNOTATION = jax_mod.profiler.TraceAnnotation
    except AttributeError:  # pragma: no cover - exotic jax build
        return None
    return _ANNOTATION


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
def configure(mode: Optional[str] = None,
              trace_dir: Optional[str] = None) -> None:
    """Set the process-global telemetry policy.  ``mode=None`` /
    ``trace_dir=None`` leave the respective setting untouched (the
    no-clobber convention `parallel.collective.configure` uses, so a
    Booster constructed without telemetry params never disarms a policy
    another layer armed)."""
    global _mode, _trace_dir, _METRICS, _TRACE
    with _state_lock:
        if mode is not None:
            m = str(mode).strip().lower()
            if m not in MODES:
                raise ValueError(
                    f"tpu_telemetry must be one of {MODES}, got {mode!r}")
            _mode = m
            _METRICS = m in ("metrics", "trace")
            _TRACE = m == "trace"
        if trace_dir is not None:
            _trace_dir = str(trace_dir)


def configure_from_config(config) -> None:
    """Apply the ``tpu_telemetry`` / ``tpu_trace_dir`` / ``tpu_obs_*``
    params from a Config.  The registry defaults ("" / 0) mean UNSET
    (leave the process policy); an explicit value — including "off" —
    really applies."""
    mode = str(config.tpu_telemetry).strip()
    tdir = str(config.tpu_trace_dir).strip()
    configure(mode=mode or None, trace_dir=tdir or None)
    from . import flightrecorder, metrics

    ring = int(config.tpu_obs_ring_samples)
    if ring > 0:
        metrics.set_sample_ring(ring)
    bb_events = int(config.tpu_obs_blackbox_events)
    bb_dir = str(config.tpu_obs_blackbox_dir).strip()
    flightrecorder.configure(events=bb_events if bb_events > 0 else None,
                             dump_dir=bb_dir or None)


def _env_init() -> None:
    mode = os.environ.get("LIGHTGBM_TPU_TELEMETRY", "").strip()
    tdir = os.environ.get("LIGHTGBM_TPU_TRACE_DIR", "").strip()
    if mode or tdir:
        configure(mode=mode or None, trace_dir=tdir or None)


def mode() -> str:
    return _mode


def trace_dir() -> str:
    return _trace_dir


def metrics_on() -> bool:
    """True under ``metrics`` or ``trace`` — the per-iteration hot-path
    gate for registry writes."""
    return _METRICS


def tracing_on() -> bool:
    return _TRACE


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def _now_us() -> float:
    return (time.perf_counter_ns() - _T0_NS) / 1e3


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _Span:
    __slots__ = ("name", "tags", "t0", "_ann")

    def __init__(self, name: str, tags: Dict):
        self.name = name
        self.tags = tags
        self.t0 = 0.0
        self._ann = None

    def __enter__(self) -> "_Span":
        st = _stack()
        if st:
            self.tags.setdefault("parent", st[-1].name)
        self.tags.setdefault("depth", len(st))
        st.append(self)
        ann_cls = _annotation_cls()
        if ann_cls is not None:
            try:
                self._ann = ann_cls(self.name)
                self._ann.__enter__()
            except Exception:  # pragma: no cover - profiler unavailable
                self._ann = None
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc) -> None:
        dur = _now_us() - self.t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:  # pragma: no cover
                pass
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        _record({
            "kind": "span", "name": self.name, "ph": "X",
            "ts": self.t0, "dur": dur,
            "host": _host_index(), "tid": threading.get_ident() % 100000,
            "tags": self.tags,
        })


def span(_name: str, **tags):
    """A nested span context manager; the shared null CM when tracing is
    off (no per-call allocation beyond the kwargs dict).  The span-name
    parameter is underscored so tags may themselves be called ``name``."""
    if not _TRACE:
        return _NULL
    return _Span(_name, tags)


def event(_name: str, **fields) -> None:
    """One structured instant event (collective timeout, watchdog
    recovery, guard firing): an ``i``-phase Chrome event plus a JSONL
    line, recorded whenever TRACING is on.  Counters for these events
    live in the registry regardless of mode — this is the narrative
    record, not the count."""
    if not _TRACE:
        return
    _record({
        "kind": "event", "name": _name, "ph": "i",
        "ts": _now_us(), "dur": 0.0,
        "host": _host_index(), "tid": threading.get_ident() % 100000,
        "tags": fields,
    })


@contextlib.contextmanager
def timed(name: str, metric: str = "lgbm_timed_seconds"):
    """Wall-clock a block into the registry (histogram `metric`, label
    ``name=``) and, under trace mode, a span.  The raw per-repeat walls
    read back via ``REGISTRY.histogram_samples`` — the bench's
    stopwatch replacement."""
    if not _METRICS:
        yield
        return
    sp = span(name)
    t0 = time.perf_counter()
    try:
        with sp:
            yield
    finally:
        # record in finally, like timer.PHASE: a raising block must not
        # leave the span recorded but the registry sample missing
        REGISTRY.observe(metric, time.perf_counter() - t0, name=name)


# ---------------------------------------------------------------------------
# recording / export
# ---------------------------------------------------------------------------
def _record(ev: Dict) -> None:
    global _dropped
    # mirror into the always-on flight recorder FIRST: the blackbox
    # ring is independently bounded, so a full trace buffer (the
    # _EVENT_CAP drop path below) must not silence it
    from . import flightrecorder

    flightrecorder.note(ev["kind"], ev["name"], **(ev["tags"] or {}))
    with _events_lock:
        if len(_events) >= _EVENT_CAP:
            _dropped += 1
            REGISTRY.inc("lgbm_trace_events_dropped_total")
            return
        _events.append(ev)
    if _trace_dir:
        _stream_write(ev)


def _stream_write(ev: Dict) -> None:
    global _stream, _stream_path
    line = json.dumps({
        "kind": ev["kind"], "name": ev["name"], "ts_us": round(ev["ts"], 3),
        "dur_us": round(ev["dur"], 3), "host": ev["host"],
        "tid": ev["tid"], "tags": ev["tags"],
    })
    with _stream_lock:
        path = os.path.join(_trace_dir,
                            f"events-host{_host_index()}.jsonl")
        try:
            if _stream is None or _stream_path != path:
                if _stream is not None:
                    _stream.close()
                os.makedirs(_trace_dir, exist_ok=True)
                _stream = open(path, "a")
                _stream_path = path
            _stream.write(line + "\n")
            _stream.flush()
        except OSError:  # pragma: no cover - disk full / perms
            pass


def events() -> List[Dict]:
    with _events_lock:
        return list(_events)


def reset_events() -> None:
    """Drop the buffered spans (tests / fresh profiling windows); the
    JSONL stream on disk is untouched."""
    global _dropped
    with _events_lock:
        _events.clear()
        _dropped = 0


def chrome_trace() -> Dict:
    """The buffered spans as a Chrome trace-event JSON object (Perfetto
    opens it directly; chrome://tracing too)."""
    host = _host_index()
    out = [{
        "name": "process_name", "ph": "M", "pid": host, "tid": 0,
        "args": {"name": f"lightgbm_tpu host {host}"},
    }]
    with _events_lock:
        evs = list(_events)
    for ev in evs:
        rec = {"name": ev["name"], "ph": ev["ph"],
               "ts": round(ev["ts"], 3), "pid": ev["host"],
               "tid": ev["tid"], "args": dict(ev["tags"])}
        if ev["ph"] == "X":
            rec["dur"] = round(ev["dur"], 3)
        else:
            rec["s"] = "t"  # instant-event scope
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(path: Optional[str] = None) -> Optional[str]:
    """Dump the buffered spans as ``trace-host<k>.json`` under
    ``tpu_trace_dir`` (or an explicit path).  Returns the written path,
    or None when there is nowhere to write."""
    if path is None:
        if not _trace_dir:
            return None
        os.makedirs(_trace_dir, exist_ok=True)
        path = os.path.join(_trace_dir,
                            f"trace-host{_host_index()}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(chrome_trace(), f)
    os.replace(tmp, path)
    return path


def flush() -> None:
    """Flush/close the JSONL stream (end of train, interpreter exit)."""
    global _stream
    with _stream_lock:
        if _stream is not None:
            try:
                _stream.flush()
                _stream.close()
            except OSError:  # pragma: no cover
                pass
            _stream = None


_env_init()
