"""Process-global metrics registry: labeled counters, gauges, and
fixed-bucket histograms.

The reference ships only ad-hoc wall-clock logging (``Log::Info`` TIMETAG
dumps, src/treelearner/serial_tree_learner.cpp:21-48); every phase of
this repo's own history that went unobserved cost a postmortem (the
degraded-CPU bench rounds, the r04→r05 container-variance "regression").
The registry is the one sink every layer writes:

* **counters** — monotonic totals (``lgbm_collective_timeouts_total``,
  ``lgbm_log_warnings_total``), labeled (``phase="sketch"``).
* **gauges** — last-write-wins levels (serving queue depth).
* **histograms** — fixed upper-bound buckets, Prometheus-style
  cumulative export plus a bounded ring of raw samples so callers that
  need per-repeat walls (bench segments) can read them back without a
  second stopwatch.  ``quantile()`` is the ONE percentile estimator —
  the serving ``/stats`` endpoint and the ``/metrics`` Prometheus
  export both derive from the same buckets, so they can never disagree.

Everything is thread-safe under per-family locks; creation is cached so
the steady-state cost of an update is one lock + one dict write.  The
registry itself is ALWAYS live (rare but vital events — watchdog
timeouts, guard firings, log warnings — record unconditionally); the
``tpu_telemetry`` gate lives in `obs.trace` and is consulted only by
the per-iteration hot-path instrumentation sites.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils import lockcheck

# default seconds buckets: wide enough for ingest phases (minutes) and
# fine enough for serving latencies (sub-ms)
DEFAULT_SECONDS_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# raw samples kept per histogram child (newest-first readback for bench
# segment medians AND the serving admission controller's recent-window
# SLO projection, which reads the last ServingStats._RECENT = 256 —
# keep this ring at least that deep); bounded so long runs cannot grow
# memory.  Configurable via tpu_obs_ring_samples (set_sample_ring);
# readers that care whether the ring dropped samples ask
# histogram_samples(..., with_truncated=True).
DEFAULT_SAMPLE_RING = 256
_sample_ring = DEFAULT_SAMPLE_RING


def set_sample_ring(n: int) -> None:
    """Resize the per-histogram raw-sample ring (process-global; wired
    from ``tpu_obs_ring_samples``).  Existing rings shrink lazily on
    their next observe; floor 1 so readback always sees the newest
    sample."""
    global _sample_ring
    _sample_ring = max(int(n), 1)


def sample_ring() -> int:
    return _sample_ring


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(key) + ([extra] if extra else [])
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n")) for k, v in items)
    return "{" + body + "}"


class _Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0


class _Gauge:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0


class _Histogram:
    __slots__ = ("bounds", "counts", "sum", "count", "samples",
                 "samples_truncated")

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        self.bounds = bounds                     # finite upper bounds
        self.counts = [0] * (len(bounds) + 1)    # + the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.samples: List[float] = []           # bounded ring
        self.samples_truncated = False           # ring ever dropped one

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1
        self.samples.append(v)
        if len(self.samples) > _sample_ring:
            del self.samples[:len(self.samples) - _sample_ring]
            self.samples_truncated = True

    def quantile(self, q: float) -> float:
        """Prometheus histogram_quantile: linear interpolation inside
        the bucket holding rank q*count (first bucket interpolates from
        0; the +Inf bucket degrades to the last finite bound)."""
        if self.count <= 0:
            return 0.0
        rank = max(min(float(q), 1.0), 0.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c > 0 and cum + c >= rank:
                if i >= len(self.bounds):        # +Inf bucket
                    return self.bounds[-1] if self.bounds else 0.0
                lower = self.bounds[i - 1] if i > 0 else 0.0
                upper = self.bounds[i]
                return lower + (upper - lower) * (rank - cum) / c
            cum += c
        return self.bounds[-1] if self.bounds else 0.0


def histogram_quantile(bounds: Iterable[float], counts: Iterable[int],
                       q: float) -> float:
    """The registry's quantile estimator over externally-held buckets —
    exported so a Prometheus scrape (bucket counts parsed back out of
    the text format) can reproduce `/stats` percentiles EXACTLY."""
    h = _Histogram(tuple(bounds))
    h.counts = list(counts)
    h.count = sum(h.counts)
    return h.quantile(q)


class _Family:
    """All children (label combinations) of one metric name."""

    def __init__(self, name: str, kind: str, help_text: str = "",
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.kind = kind                        # counter | gauge | histogram
        self.help = help_text
        self.buckets = buckets
        self.lock = lockcheck.make_lock(f"obs.metrics.family:{name}")
        self.children: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def child(self, labels: Dict[str, str]):
        key = _label_key(labels)
        with self.lock:
            c = self.children.get(key)
            if c is None:
                if self.kind == "counter":
                    c = _Counter()
                elif self.kind == "gauge":
                    c = _Gauge()
                else:
                    c = _Histogram(self.buckets or DEFAULT_SECONDS_BUCKETS)
                self.children[key] = c
            return c


class MetricsRegistry:
    """Thread-safe named-metric store with Prometheus text export.

    One process-global instance (`REGISTRY`) serves training/distributed/
    checkpoint telemetry; the serving stack holds a private instance per
    session so concurrent sessions (tests) never cross-count.
    """

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("obs.metrics.registry")
        self._families: Dict[str, _Family] = {}

    # -- family creation/lookup ----------------------------------------
    def _family(self, name: str, kind: str, help_text: str = "",
                buckets: Optional[Tuple[float, ...]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = _Family(name, kind, help_text, buckets)
                    self._families[name] = fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"not {kind}")
        return fam

    # -- writes --------------------------------------------------------
    def inc(self, _name: str, n: float = 1, help: str = "",
            **labels: str) -> None:
        # metric-name params are underscored so label kwargs may be
        # called `name` (collective wait times label by collective name)
        fam = self._family(_name, "counter", help)
        c = fam.child(labels)
        with fam.lock:
            c.value += n

    def set_gauge(self, _name: str, v: float, help: str = "",
                  **labels: str) -> None:
        fam = self._family(_name, "gauge", help)
        c = fam.child(labels)
        with fam.lock:
            c.value = float(v)

    def observe(self, _name: str, v: float,
                buckets: Optional[Tuple[float, ...]] = None,
                help: str = "", **labels: str) -> None:
        fam = self._family(_name, "histogram", help, buckets)
        h = fam.child(labels)
        with fam.lock:
            h.observe(float(v))

    # -- reads ---------------------------------------------------------
    def value(self, _name: str, **labels: str) -> float:
        """Counter/gauge value (0.0 when the child does not exist)."""
        fam = self._families.get(_name)
        if fam is None:
            return 0.0
        c = fam.children.get(_label_key(labels))
        return 0.0 if c is None else float(c.value)

    def histogram_quantile(self, _name: str, q: float,
                           **labels: str) -> float:
        fam = self._families.get(_name)
        if fam is None:
            return 0.0
        h = fam.children.get(_label_key(labels))
        return 0.0 if h is None else h.quantile(q)

    def histogram_samples(self, _name: str, with_truncated: bool = False,
                          **labels: str):
        """The bounded raw-sample ring (newest last) — per-repeat walls
        for callers like bench that need medians, not just buckets.

        ``with_truncated=True`` returns ``(samples, truncated)`` where
        `truncated` reports whether the ring EVER dropped a sample for
        this child — so a repeat-readback loop can tell "all my repeats
        are here" from "the ring silently under-counts"."""
        fam = self._families.get(_name)
        if fam is None:
            return ([], False) if with_truncated else []
        h = fam.children.get(_label_key(labels))
        if h is None:
            return ([], False) if with_truncated else []
        with fam.lock:
            samples = list(h.samples)
            truncated = bool(h.samples_truncated)
        return (samples, truncated) if with_truncated else samples

    def histogram_stats(self, _name: str, **labels: str
                        ) -> Tuple[int, float]:
        """(count, sum) of one histogram child."""
        fam = self._families.get(_name)
        if fam is None:
            return 0, 0.0
        h = fam.children.get(_label_key(labels))
        return (0, 0.0) if h is None else (h.count, h.sum)

    def label_values(self, name: str, label: str) -> List[str]:
        """Distinct values one label takes across a family's children."""
        fam = self._families.get(name)
        if fam is None:
            return []
        out = []
        with fam.lock:
            for key in fam.children:
                for k, v in key:
                    if k == label and v not in out:
                        out.append(v)
        return sorted(out)

    def snapshot(self) -> Dict:
        """Plain-dict dump (tests, JSONL flushes)."""
        out: Dict = {}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            with fam.lock:
                for key, c in fam.children.items():
                    tag = fam.name + _fmt_labels(key)
                    if fam.kind == "histogram":
                        out[tag] = {"count": c.count, "sum": c.sum}
                    else:
                        out[tag] = c.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    def clear_family(self, _name: str) -> None:
        """Drop one metric family's children (the family itself and its
        type registration survive) — partial resets like the bench
        zeroing the phase accumulation between runs."""
        fam = self._families.get(_name)
        if fam is not None:
            with fam.lock:
                fam.children.clear()

    def remove(self, _name: str, **labels: str) -> None:
        """Drop ONE labeled child — per-entity gauges (a serving
        model's HBM bytes) must disappear with the entity, or a
        long-lived hot-swapping server grows one dead series per
        version ever loaded."""
        fam = self._families.get(_name)
        if fam is not None:
            with fam.lock:
                fam.children.pop(_label_key(labels), None)

    # -- Prometheus text exposition (version 0.0.4) --------------------
    def to_prometheus_text(self) -> str:
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            with fam.lock:
                children = list(fam.children.items())
            for key, c in sorted(children):
                if fam.kind in ("counter", "gauge"):
                    lines.append(f"{fam.name}{_fmt_labels(key)} {c.value:g}")
                    continue
                cum = 0
                for ub, cnt in zip(c.bounds, c.counts):
                    cum += cnt
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_fmt_labels(key, ('le', repr(float(ub))))} {cum}")
                cum += c.counts[-1]
                lines.append(
                    f"{fam.name}_bucket"
                    f"{_fmt_labels(key, ('le', '+Inf'))} {cum}")
                lines.append(f"{fam.name}_sum{_fmt_labels(key)} {c.sum:g}")
                lines.append(f"{fam.name}_count{_fmt_labels(key)} {c.count}")
        return "\n".join(lines) + ("\n" if lines else "")


#: the process-global registry every non-serving layer writes to
REGISTRY = MetricsRegistry()
