"""Resource observability: device HBM accounting + process runtime stats.

ROADMAP items 1 (out-of-core streaming) and 2c (quantized serving
tables) are both HBM-budget problems, yet until ISSUE 12 nothing in the
codebase could say what the [L, G/P, B, 3] histogram pool or a packed
forest actually costs on device — block-size and models-per-HBM
decisions (the *Out-of-Core GPU Gradient Boosting* trade, PAPERS.md
arXiv 2005.09148) live or die on exactly that number.  This module is
the one place that reads it:

* **device gauges** — `device_memory_stats()` wraps
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``
  on TPU/GPU; the CPU backend returns None and every caller here
  degrades gracefully to None instead of inventing a number).
* **phase watermarks** — `phase_peak(phase)` brackets one lifecycle
  phase (ingest / hist_build / score_update / predict, the PR-10 span
  boundaries) and records the peak HBM the phase owned.  XLA exposes no
  per-phase peak reset, so the bracket emulates reset-and-read: if the
  process-wide ``peak_bytes_in_use`` grew inside the bracket the phase
  owns the new peak; otherwise the phase is bounded by the live
  ``bytes_in_use`` it saw.  Gated on `obs.metrics_on()` — the off-mode
  train loop pays one flag check.
* **process runtime stats** — `process_runtime_stats()` (RSS, uptime,
  threads, open fds, GC collections): flat /proc + stdlib reads, no new
  deps, published as gauges on the serving ``GET /metrics`` / ``/stats``
  endpoints.
* **bench metrics** — `bench_resource_metrics()` packages the above
  plus the CompileLedger's per-program cost capture into the
  ``train_peak_hbm_bytes`` / ``program_costs`` bench fields (explicitly
  None on CPU rather than silently absent).

Nothing here ever forces a backend init: jax is consulted only when the
caller already imported it.
"""

from __future__ import annotations

import contextlib
import gc
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from .metrics import REGISTRY
from .trace import metrics_on

#: the phase vocabulary the watermark gauges use (PR-10 span boundaries)
PHASES = ("ingest", "hist_build", "score_update", "predict")

_PEAK_GAUGE = "lgbm_device_phase_peak_bytes"

# process-start anchor for uptime (obs imports at package import, so
# this is within milliseconds of interpreter start for any real run)
_T_START = time.time()

_lock = threading.Lock()
_phase_peaks: Dict[str, int] = {}


def _devices():
    """Already-initialized jax devices, or [] — resource accounting must
    never be the thing that forces (or hangs) backend init."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return []
    try:
        return list(jax_mod.devices())
    except Exception:  # pragma: no cover - backend init failure
        return []


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """One device's ``memory_stats()`` dict, or None when the backend
    does not report (CPU) or jax is not imported yet."""
    if device is None:
        devs = _devices()
        if not devs:
            return None
        device = devs[0]
    try:
        stats = device.memory_stats()
    except Exception:  # pragma: no cover - exotic plugin
        return None
    if not stats:
        return None
    return {str(k): int(v) for k, v in stats.items()}


def all_device_memory_stats() -> List[Optional[Dict[str, int]]]:
    """Per-device memory_stats (None entries for non-reporting devices)."""
    return [device_memory_stats(d) for d in _devices()]


def hbm_bytes_in_use() -> Optional[int]:
    """Max ``bytes_in_use`` across reporting devices; None on CPU."""
    vals = [s.get("bytes_in_use") for s in all_device_memory_stats()
            if s is not None and s.get("bytes_in_use") is not None]
    return max(vals) if vals else None


def peak_hbm_bytes() -> Optional[int]:
    """Max ``peak_bytes_in_use`` across reporting devices; None on CPU
    (the value the ``train_peak_hbm_bytes`` bench metric records)."""
    vals = []
    for s in all_device_memory_stats():
        if s is None:
            continue
        v = s.get("peak_bytes_in_use", s.get("bytes_in_use"))
        if v is not None:
            vals.append(v)
    return max(vals) if vals else None


# ---------------------------------------------------------------------------
# phase-tagged peak watermarks
# ---------------------------------------------------------------------------
def _note_phase_peak(phase: str, peak: int) -> None:
    with _lock:
        prev = _phase_peaks.get(phase, 0)
        if peak <= prev:
            return
        _phase_peaks[phase] = int(peak)
        # gauge write INSIDE the lock: a racing smaller peak must not
        # overwrite a larger one on the exported surface
        REGISTRY.set_gauge(_PEAK_GAUGE, int(peak),
                           help="peak device bytes owned by one "
                                "lifecycle phase (reset-and-read "
                                "watermark)",
                           phase=phase)


#: shared no-op CM handed back when metrics are off — the per-iteration
#: hot path pays one flag check + one allocation-free return, the same
#: discipline obs.span uses
_NULL = contextlib.nullcontext()


def _fleet_watermark() -> Optional[tuple]:
    """(max peak, max bytes_in_use) across ALL reporting devices, or
    None — the phase table must aggregate the same way
    `peak_hbm_bytes()` does, or a sharded phase peaking on a non-zero
    device could not explain the train peak it sits next to."""
    peaks, in_use = [], []
    for s in all_device_memory_stats():
        if s is None:
            continue
        peaks.append(s.get("peak_bytes_in_use", 0))
        in_use.append(s.get("bytes_in_use", 0))
    if not peaks:
        return None
    return max(peaks), max(in_use)


class _PhasePeak:
    __slots__ = ("phase", "_p0", "_b0")

    def __init__(self, phase: str):
        self.phase = phase

    def __enter__(self) -> "_PhasePeak":
        before = _fleet_watermark()
        if before is None:      # CPU / no backend: graceful None
            self._p0 = None
        else:
            self._p0, self._b0 = before
        return self

    def __exit__(self, *exc) -> None:
        if self._p0 is None:
            return
        after = _fleet_watermark()
        if after is None:  # pragma: no cover - backend vanished
            return
        p1, b1 = after
        # process-wide peak grew inside the bracket -> this phase owns
        # the new watermark; else bound by the live bytes seen
        _note_phase_peak(self.phase,
                         p1 if p1 > self._p0 else max(self._b0, b1))


def phase_peak(phase: str):
    """Bracket one lifecycle phase and record its peak HBM watermark.

    The shared null CM (no allocation) when telemetry metrics are off;
    on CPU the bracket runs but records nothing (memory_stats is
    None)."""
    if not metrics_on():
        return _NULL
    return _PhasePeak(phase)


def phase_peaks() -> Dict[str, int]:
    """Phase -> peak device bytes recorded so far ({} on CPU)."""
    with _lock:
        return dict(_phase_peaks)


def reset_phase_peaks() -> None:
    with _lock:
        _phase_peaks.clear()
    REGISTRY.clear_family(_PEAK_GAUGE)


# ---------------------------------------------------------------------------
# process runtime stats (satellite: /metrics + /stats gauges)
# ---------------------------------------------------------------------------
def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:  # pragma: no cover - non-procfs host
            import resource

            peak = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
            # linux reports KiB, darwin bytes; either way this is the
            # PEAK rss — the best a non-procfs host can offer
            return peak if sys.platform == "darwin" else peak * 1024
        except Exception:  # pragma: no cover
            return None


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # pragma: no cover - non-procfs host
        return None


def process_runtime_stats() -> Dict[str, Optional[float]]:
    """Flat process-runtime reads: RSS, uptime, threads, open fds, GC
    collections.  Every value is cheap (one /proc read or stdlib call);
    an unavailable source reports an explicit None — never a fictional
    0 an fd-leak alert would read as a measurement."""
    rss = _rss_bytes()
    fds = _open_fds()
    return {
        "process_rss_bytes": int(rss) if rss is not None else None,
        "process_uptime_s": round(time.time() - _T_START, 3),
        "process_threads": threading.active_count(),
        "process_open_fds": int(fds) if fds is not None else None,
        "process_gc_collections": sum(
            s.get("collections", 0) for s in gc.get_stats()),
    }


def publish_process_gauges(registry=None) -> Dict[str, float]:
    """Refresh the process-runtime gauges in `registry` (default: the
    process-global one) — called per /metrics scrape so the exported
    values are scrape-time reads, not stale snapshots."""
    reg = REGISTRY if registry is None else registry
    stats = process_runtime_stats()
    names = {
        "process_rss_bytes": ("lgbm_process_resident_memory_bytes",
                              "resident set size"),
        "process_uptime_s": ("lgbm_process_uptime_seconds",
                             "seconds since process start"),
        "process_threads": ("lgbm_process_threads",
                            "live python threads"),
        "process_open_fds": ("lgbm_process_open_fds",
                             "open file descriptors"),
        "process_gc_collections": ("lgbm_process_gc_collections",
                                   "cumulative gc collections across "
                                   "generations"),
    }
    for key, (metric, help_text) in names.items():
        if stats[key] is None:
            continue   # unmeasurable here: no series beats a fiction
        reg.set_gauge(metric, float(stats[key]), help=help_text)
    return stats


# ---------------------------------------------------------------------------
# bench packaging
# ---------------------------------------------------------------------------
def bench_resource_metrics(ledger=None, memory: Optional[bool] = None,
                           train_peak: Optional[int] = None) -> Dict:
    """The resource fields a bench/smoke record carries:

    * ``train_peak_hbm_bytes`` — peak device bytes (None on CPU).
      Pass ``train_peak`` snapshotted right after the train segments
      (bench does): ``peak_bytes_in_use`` is a process-lifetime
      high-water mark, so a call-time read after predict/serve would
      attribute THEIR peaks to training.  Without a snapshot the field
      is the process peak so far.
    * ``phase_peak_hbm_bytes`` — phase -> watermark dict (None on CPU),
    * ``program_costs`` — the CompileLedger's per-site cost rollup
      (flops / bytes accessed everywhere; temp/arg/output bytes only
      where a compiled memory_analysis exists — None per field on CPU
      unless ``memory=True`` forces the recompile-based capture).

    Explicit None beats silent absence: a reader of the JSON can tell
    "not measurable on this backend" from "forgot to measure".
    """
    if ledger is None:
        from ..utils.compile_ledger import LEDGER as ledger
    peaks = phase_peaks()
    return {
        "train_peak_hbm_bytes": (peak_hbm_bytes() if train_peak is None
                                 else train_peak),
        "phase_peak_hbm_bytes": peaks if peaks else None,
        "program_costs": ledger.cost_table(memory=memory) or None,
    }
