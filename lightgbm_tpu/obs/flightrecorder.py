"""Always-on flight recorder: a bounded process-global ring of the last
N spans, instant events, watchdog/guard/breaker transitions and metric
snapshots, dumped atomically to ``blackbox-host<k>.json`` when the
process dies badly.

PR 8's watchdogs turn distributed hangs into structured errors, but the
evidence of *what the process was doing* died with it unless tracing
was pre-enabled.  The recorder closes that gap: it runs EVEN AT
``tpu_telemetry=off`` (so it must stay inside the <1% off-mode overhead
gate — one `note()` is a clock read + tuple + GIL-atomic deque append,
recorded only at coarse boundaries: per training round, per collective,
per state transition — never inside the per-row hot loops), and under
``tpu_telemetry=trace`` every buffered span/event mirrors in as well.

Dump triggers (all funnel through `dump(reason)`, atomic tmp+rename):

* unhandled exception — a `sys.excepthook` chain installed at import;
* `CollectiveTimeout` / `HostDropped` — `parallel.collective` dumps
  before re-raising, so the newest ring entries name the in-flight
  collective (the ``span_begin`` without a matching ``span_end``);
* SIGTERM / interrupt / XLA error mid-train — `engine.train`'s
  recovery path dumps AFTER the final checkpoint flush (the dump's
  metric snapshot then proves the checkpoint landed first);
* ``tpu_guard_numerics=raise`` firings — `models.gbdt` dumps beside
  the structured error;
* faultline-injected crashes ride the paths above (an injected raise
  propagates through the train loop's recovery, an injected hang
  through the watchdog).

The serving server exposes the live ring as ``GET /debug/blackbox``;
``tools/trace_merge.py --blackbox`` overlays multiple hosts' dumps
(entries carry wall-clock epoch seconds, comparable across hosts) to
answer "who hung first".

The dump directory resolves: `configure(dump_dir=...)` (the
``tpu_obs_blackbox_dir`` param) > ``LIGHTGBM_TPU_BLACKBOX_DIR`` env >
the live ``tpu_trace_dir`` > the working directory — EXCEPT when the
working directory is a source checkout (a ``.git`` entry is present),
which falls through to the system temp dir instead: .gitignore or
not, a crash artifact must never regrow at a repo root and ride into
a commit.  Wherever it lands, the FILENAME is always the canonical
``blackbox-host<k>.json``; callers that pass `path=` a directory get
the canonical name joined under it.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..utils import lockcheck

DEFAULT_EVENTS = 512

# the ring: GIL-atomic appends (deque with maxlen), no lock on the
# record path.  Entries are tuples
# (epoch_s, kind, name, tid, fields-or-None) — dicts materialize only
# at dump/read time.
_ring: deque = deque(maxlen=DEFAULT_EVENTS)

_dump_lock = lockcheck.make_lock("obs.flightrecorder.dump")
_dump_dir = ""
_last_dump: Optional[str] = None
_dumps = 0


def _host_index() -> int:
    from ..utils import faultline

    return faultline.host_index()


def configure(events: Optional[int] = None,
              dump_dir: Optional[str] = None) -> None:
    """Resize the ring / set the dump directory.  None leaves the
    respective setting untouched (the obs no-clobber convention);
    resizing keeps the newest entries."""
    global _ring, _dump_dir
    if events is not None:
        n = max(int(events), 16)
        if n != _ring.maxlen:
            _ring = deque(list(_ring)[-n:], maxlen=n)
    if dump_dir is not None:
        _dump_dir = str(dump_dir)


def depth() -> int:
    return int(_ring.maxlen or DEFAULT_EVENTS)


def note(_kind: str, _name: str, **fields) -> None:
    """One flight-recorder entry.  Always on; called only at coarse
    boundaries (round, collective, transition) so the off-mode overhead
    gate holds.  The deque append is GIL-atomic — no lock."""
    _ring.append((time.time(), _kind, _name,
                  threading.get_ident() % 100000, fields or None))


def entries() -> List[Dict]:
    """The ring as dicts, oldest first (a live read, used by the
    serving ``GET /debug/blackbox`` route and the dump)."""
    out = []
    for t, kind, name, tid, fields in list(_ring):
        rec = {"t": round(t, 6), "kind": kind, "name": name, "tid": tid}
        if fields:
            rec["fields"] = {k: _jsonable(v) for k, v in fields.items()}
        out.append(rec)
    return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def reset() -> None:
    """Clear the ring (tests / fresh windows); configuration persists."""
    global _last_dump, _dumps
    _ring.clear()
    # under the dump lock like dump() itself: a reset racing a crash
    # dump must not interleave with its _last_dump/_dumps writes
    # (found by graftlint C301 — the ring clear above stays lock-free
    # by design, deque ops are GIL-atomic)
    with _dump_lock:
        _last_dump = None
        _dumps = 0


def last_dump() -> Optional[str]:
    return _last_dump


def blackbox_dir() -> str:
    if _dump_dir:
        return _dump_dir
    env = os.environ.get("LIGHTGBM_TPU_BLACKBOX_DIR", "")
    if env:
        return env
    from .trace import trace_dir

    td = trace_dir()
    if td:
        return td
    cwd = os.getcwd()
    if os.path.exists(os.path.join(cwd, ".git")):
        # a source checkout: a crash dump written here would sit at the
        # repo root waiting to be committed — park it in temp instead
        # (an EXPLICIT dir via param/env/path is always honored as-is)
        import tempfile

        return tempfile.gettempdir()
    return cwd


def dump(reason: str, path: Optional[str] = None,
         exc: Optional[BaseException] = None) -> Optional[str]:
    """Write the blackbox: ring entries (oldest first) + a registry
    metric snapshot + crash metadata, atomically (tmp + rename — a
    second crash mid-dump never leaves a torn file).  Repeated dumps
    overwrite ``blackbox-host<k>.json`` in place: the newest death is
    the one worth reading.  Never raises — the recorder must not turn
    a crash into a different crash."""
    global _last_dump, _dumps
    from .metrics import REGISTRY

    try:
        host = _host_index()
        if path is None:
            d = blackbox_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"blackbox-host{host}.json")
        elif os.path.isdir(path):
            # a directory keeps the canonical (gitignored) filename —
            # only an explicit FILE path may rename the dump
            path = os.path.join(path, f"blackbox-host{host}.json")
        record = {
            "reason": str(reason),
            "host": host,
            "pid": os.getpid(),
            "t": round(time.time(), 6),
            "ring_depth": depth(),
            "entries": entries(),          # oldest first; tail = newest
            "metrics": REGISTRY.snapshot(),
        }
        if exc is not None:
            record["exception"] = {"type": type(exc).__name__,
                                   "message": str(exc)}
        tmp = f"{path}.tmp.{os.getpid()}"
        with _dump_lock:
            with open(tmp, "w") as f:
                json.dump(record, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _last_dump = path
            _dumps += 1
        return path
    except Exception:  # pragma: no cover - disk full / perms
        return None


# ---------------------------------------------------------------------------
# unhandled-exception hooks (chained, installed once at import).  BOTH
# hooks: sys.excepthook never fires for non-main threads, and the
# serving runtime the recorder targets IS multithreaded (batcher
# worker, dispatch runners, HTTP handlers) — threading.excepthook
# covers those deaths.
# ---------------------------------------------------------------------------
_prev_excepthook = None
_prev_thread_hook = None


def _excepthook(exc_type, exc, tb):  # pragma: no cover - process death
    note("crash", "unhandled_exception", type=exc_type.__name__,
         message=str(exc)[:200])
    dump("unhandled_exception", exc=exc)
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _thread_excepthook(args):  # pragma: no cover - thread death
    note("crash", "unhandled_thread_exception",
         type=args.exc_type.__name__, message=str(args.exc_value)[:200],
         thread=getattr(args.thread, "name", "?"))
    dump("unhandled_thread_exception", exc=args.exc_value)
    if _prev_thread_hook is not None:
        _prev_thread_hook(args)


def _install_excepthook() -> None:
    global _prev_excepthook, _prev_thread_hook
    if sys.excepthook is not _excepthook:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _excepthook
    if threading.excepthook is not _thread_excepthook:
        _prev_thread_hook = threading.excepthook
        threading.excepthook = _thread_excepthook


_install_excepthook()
