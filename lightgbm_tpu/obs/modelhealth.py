"""Model & data health: training reference profiles and serving-side
binned drift detection (ISSUE 14).

The framework's binned representation makes drift detection nearly
free: serving already maps every raw row through the TRAINING bin
mappers (the `tpu_bin_mappers:` snapshot), so "has the input
distribution moved off the training data?" reduces to comparing
per-feature bin occupancy against the occupancy captured at train time
(reference ``BinMapper::cnt_in_bin``; the binned/quantized-matrix
design of arXiv 1806.11248).

Two halves:

* `FeatureProfile` — the training reference: per-feature bin-occupancy
  counts, NaN/zero fractions, label stats, and the raw-score histogram,
  captured at train end and serialized as a compact
  ``tpu_feature_profile:`` model-string trailer (exactly like
  ``tpu_bin_mappers:`` — it round-trips byte-identically through
  save/load, checkpoints, and the serving registry).
* `DriftMonitor` — the serving tap: per-batch row samples
  (`serving_drift_sample_rows`) are stashed on the dispatch path with
  one deque append (GIL-atomic, NO lock, no device work), then binned
  and accumulated lazily at scrape time (`/drift`, `/metrics`,
  `snapshot()`), off the dispatch hot path.  Divergences are PSI and
  Jensen-Shannon per feature plus a raw-score-histogram JS, all
  computed in float64 on the host so they match a NumPy oracle exactly
  — the sampled bin counts are exact int64, and the accumulation is
  pure integer addition (order-independent).

The accumulator is deliberately host-side numpy: the serving lifecycle
carries an exact compiled-program-count gate
(tests/test_compile_stability.py), and a jitted bincount would add a
program per launch shape for a O(sample_rows * features) integer count
that the host does in microseconds.

PSI uses add-one-half count smoothing (0.5 added to every bin before
normalizing) so empty bins cannot produce infinities; JS needs no
smoothing (0 * log 0 terms are 0 by continuity).  Both use natural
logarithms.  Conventional PSI reading: < 0.1 stable, 0.1-0.25 moderate
shift, > 0.25 major shift — `serving_drift_psi_warn` defaults to 0.25.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import lockcheck

#: model-string trailer marker (same convention as ``tpu_bin_mappers:``)
PROFILE_MARKER = "tpu_feature_profile:"

#: default raw-score histogram resolution (``tpu_profile_score_bins``)
DEFAULT_SCORE_BINS = 32

#: stashed-but-unabsorbed sample batches the monitor retains; older
#: batches drop silently (it is a SAMPLING monitor — a scrape gap must
#: bound memory, not grow it)
PENDING_BATCHES = 64


# ---------------------------------------------------------------------------
# divergences (float64 host math — the oracle IS the implementation)
# ---------------------------------------------------------------------------
def _proportions(counts: np.ndarray, smooth: float) -> np.ndarray:
    c = np.asarray(counts, np.float64) + np.float64(smooth)
    return c / c.sum()


def psi(expected: Sequence[float], observed: Sequence[float]) -> float:
    """Population Stability Index between two count vectors.

    ``sum((o_i - e_i) * ln(o_i / e_i))`` over add-0.5-smoothed,
    normalized proportions, in float64.  Returns 0.0 when either side
    carries no counts (no evidence is not drift)."""
    e = np.asarray(expected, np.float64)
    o = np.asarray(observed, np.float64)
    if e.size == 0 or e.sum() <= 0 or o.sum() <= 0:
        return 0.0
    ep = _proportions(e, 0.5)
    op = _proportions(o, 0.5)
    return float(np.sum((op - ep) * np.log(op / ep)))


def js_divergence(expected: Sequence[float],
                  observed: Sequence[float]) -> float:
    """Jensen-Shannon divergence (natural log, so the bound is ln 2)
    between two count vectors, float64, no smoothing — zero bins
    contribute 0 by the 0*log(0)=0 convention."""
    e = np.asarray(expected, np.float64)
    o = np.asarray(observed, np.float64)
    if e.size == 0 or e.sum() <= 0 or o.sum() <= 0:
        return 0.0
    p = e / e.sum()
    q = o / o.sum()
    m = 0.5 * (p + q)
    with np.errstate(divide="ignore", invalid="ignore"):
        kl_p = np.where(p > 0, p * np.log(p / m), 0.0)
        kl_q = np.where(q > 0, q * np.log(q / m), 0.0)
    return float(0.5 * kl_p.sum() + 0.5 * kl_q.sum())


def bin_occupancy(bins: np.ndarray, num_bin: int) -> np.ndarray:
    """Exact int64 occupancy of one already-binned column."""
    return np.bincount(np.asarray(bins, np.int64),
                       minlength=int(num_bin)).astype(np.int64)


def score_hist_counts(edges: Sequence[float],
                      values: np.ndarray) -> np.ndarray:
    """int64 histogram of `values` over fixed `edges` (len B+1); out-of-
    range values clip into the boundary bins, non-finite values drop."""
    e = np.asarray(edges, np.float64)
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0 or e.size < 2:
        return np.zeros(max(e.size - 1, 0), np.int64)
    idx = np.clip(np.searchsorted(e[1:-1], v, side="right"),
                  0, e.size - 2)
    return np.bincount(idx, minlength=e.size - 1).astype(np.int64)


# ---------------------------------------------------------------------------
# training reference profile
# ---------------------------------------------------------------------------
class FeatureProfile:
    """The training-time statistical reference a drift monitor compares
    against.  Payload layout is deterministic (fixed key order, plain
    int/float JSON scalars) so `to_line()` bytes survive
    save -> load -> save unchanged."""

    def __init__(self, features: Dict[int, Dict], label: Dict,
                 score_edges: List[float], score_counts: List[List[int]]):
        self.features = features          # real feature idx -> stats
        self.label = label
        self.score_edges = score_edges
        self.score_counts = score_counts  # one count row per class

    # -- capture --------------------------------------------------------
    @classmethod
    def from_training(cls, td, feature_names: Sequence[str],
                      raw_scores: np.ndarray,
                      score_bins: int = DEFAULT_SCORE_BINS
                      ) -> Optional["FeatureProfile"]:
        """Capture from a live TrainingData + the end-of-training raw
        scores ([k, n] float).  Occupancy comes from each used mapper's
        ``cnt_in_bin`` (the reference's own sample counts); mappers
        without counts (deserialized) are skipped.  Returns None when
        nothing is capturable."""
        from ..io.bin_mapper import MissingType

        features: Dict[int, Dict] = {}
        used = list(getattr(td, "used_feature_idx", []))
        for c in used:
            m = td.mappers[c]
            cnt = [int(x) for x in m.cnt_in_bin]
            if m.is_trivial or not cnt:
                continue
            total = max(sum(cnt), 1)
            # the last bin is a NaN bin only when one actually exists:
            # numerical NAN mappers always reserve it, but a TRUNCATED
            # categorical sets missing_type=NAN with the last bin being
            # a real category plus the rare-tail remainder — counting
            # that as NaN mass would bias every nan_delta afterwards
            if int(m.bin_type) == 0:
                has_nan_bin = m.missing_type == MissingType.NAN
            else:
                has_nan_bin = (bool(m.bin_2_categorical)
                               and m.bin_2_categorical[-1] == -1)
            nan_frac = cnt[-1] / total if has_nan_bin else 0.0
            zero_frac = (cnt[m.default_bin] / total
                         if int(m.bin_type) == 0
                         and 0 <= m.default_bin < len(cnt) else 0.0)
            name = (str(feature_names[c]) if c < len(feature_names)
                    else f"Column_{c}")
            features[int(c)] = {
                "name": name, "bin_type": int(m.bin_type),
                "num_bin": int(m.num_bin), "cnt": cnt,
                "nan_frac": float(nan_frac),
                "zero_frac": float(zero_frac)}
        if not features:
            return None
        y = np.asarray(td.metadata.label, np.float64)
        label = {"n": int(y.size),
                 "mean": float(y.mean()) if y.size else 0.0,
                 "std": float(y.std()) if y.size else 0.0,
                 "min": float(y.min()) if y.size else 0.0,
                 "max": float(y.max()) if y.size else 0.0}
        s = np.asarray(raw_scores, np.float64)
        if s.ndim == 1:
            s = s[None, :]
        fin = s[np.isfinite(s)]
        lo = float(fin.min()) if fin.size else 0.0
        hi = float(fin.max()) if fin.size else 1.0
        if hi <= lo:
            hi = lo + 1.0
        nb = max(int(score_bins), 2)
        edges = [float(x) for x in np.linspace(lo, hi, nb + 1)]
        counts = [[int(x) for x in score_hist_counts(edges, row)]
                  for row in s]
        return cls(features, label, edges, counts)

    # -- serialization --------------------------------------------------
    def to_payload(self) -> Dict:
        """JSON payload, deterministic key order (features sorted by
        index) — the byte-identity contract of the trailer."""
        return {
            "version": 1,
            "features": {str(c): {
                "name": f["name"], "bin_type": int(f["bin_type"]),
                "num_bin": int(f["num_bin"]),
                "cnt": [int(x) for x in f["cnt"]],
                "nan_frac": float(f["nan_frac"]),
                "zero_frac": float(f["zero_frac"]),
            } for c, f in sorted(self.features.items())},
            "label": {k: (int(self.label[k]) if k == "n"
                          else float(self.label[k]))
                      for k in ("n", "mean", "std", "min", "max")},
            "score": {"edges": [float(x) for x in self.score_edges],
                      "counts": [[int(x) for x in row]
                                 for row in self.score_counts]},
        }

    @classmethod
    def from_payload(cls, payload: Dict) -> "FeatureProfile":
        features = {int(c): {
            "name": str(f["name"]), "bin_type": int(f["bin_type"]),
            "num_bin": int(f["num_bin"]),
            "cnt": [int(x) for x in f["cnt"]],
            "nan_frac": float(f["nan_frac"]),
            "zero_frac": float(f["zero_frac"]),
        } for c, f in payload["features"].items()}
        label = {k: (int(payload["label"][k]) if k == "n"
                     else float(payload["label"][k]))
                 for k in ("n", "mean", "std", "min", "max")}
        score = payload["score"]
        return cls(features, label,
                   [float(x) for x in score["edges"]],
                   [[int(x) for x in row] for row in score["counts"]])

    def to_line(self) -> str:
        """The full trailer line, newline-terminated."""
        return PROFILE_MARKER + json.dumps(self.to_payload()) + "\n"

    def summary(self) -> Dict:
        """Compact human-facing digest (model_report)."""
        return {
            "features": len(self.features),
            "label": dict(self.label),
            "score_bins": len(self.score_edges) - 1,
            "score_classes": len(self.score_counts),
            "nan_frac_max": max((f["nan_frac"]
                                 for f in self.features.values()),
                                default=0.0),
        }


def split_profile_trailer(text: str):
    """Split a model string into (model_text, FeatureProfile | None) —
    the ``tpu_feature_profile:`` analog of `_split_mapper_snapshot`."""
    marker = "\n" + PROFILE_MARKER
    pos = text.rfind(marker)
    if pos < 0:
        return text, None
    line_end = text.find("\n", pos + 1)
    payload = text[pos + len(marker): len(text) if line_end < 0
                   else line_end].strip()
    rest = "" if line_end < 0 else text[line_end:]
    try:
        prof = FeatureProfile.from_payload(json.loads(payload))
    except (json.JSONDecodeError, KeyError, ValueError, TypeError) as exc:
        raise ValueError(
            f"corrupt tpu_feature_profile line in model: {payload[:80]!r}"
        ) from exc
    return text[:pos] + rest, prof


# ---------------------------------------------------------------------------
# serving drift monitor
# ---------------------------------------------------------------------------
class DriftMonitor:
    """Accumulates sampled serving traffic against a `FeatureProfile`.

    Dispatch path (`tap`): stride-sample up to `sample_rows` rows of the
    batch, copy, one deque append — GIL-atomic like the flight-recorder
    ring, deliberately lock-free and device-free (C3xx: never dispatch
    or block the batcher worker).  Scrape path (`snapshot`): drain the
    pending deque, bin the samples through the TRAINING mappers, score
    them with the host walker (raw scores, matching the profile's
    histogram), and merge exact int64 counts under the monitor lock.
    """

    def __init__(self, profile: FeatureProfile, mappers: List,
                 sample_rows: int, psi_warn: float = 0.25,
                 model: str = "",
                 score_fn: Optional[Callable[[np.ndarray],
                                             np.ndarray]] = None,
                 stats=None,
                 num_feature: Optional[int] = None):
        self.profile = profile
        self.model = str(model)
        self.sample_rows = max(int(sample_rows), 0)
        self.psi_warn = float(psi_warn)
        self._score_fn = score_fn
        self._stats = stats
        self._num_feature = (int(num_feature) if num_feature is not None
                             else None)
        self._lock = lockcheck.make_lock("obs.modelhealth.monitor")
        # tracked features: profile occupancy exists AND the serving
        # mapper list can bin the column
        self.tracked: List[int] = sorted(
            c for c in profile.features
            if c < len(mappers) and not mappers[c].is_trivial
            and (num_feature is None or c < num_feature))
        self._mappers = mappers
        # pending sampled batches: GIL-atomic deque appends/pops, no
        # lock by design (bounded; oldest unscraped samples drop) —
        # the modelhealth analog of the flight-recorder ring
        self._pending: deque = deque(maxlen=PENDING_BATCHES)
        # accumulators (all guarded by _lock; see graftlint OWNERSHIP)
        self._counts: Dict[int, np.ndarray] = {
            c: np.zeros(profile.features[c]["num_bin"], np.int64)
            for c in self.tracked}
        self._nan: Dict[int, int] = {c: 0 for c in self.tracked}
        self._unseen: Dict[int, int] = {c: 0 for c in self.tracked}
        self._rows = 0
        self._score_counts = np.zeros(
            (len(profile.score_counts),
             max(len(profile.score_edges) - 1, 1)), np.int64)
        self._warned = False
        self._warnings = 0

    # -- dispatch path --------------------------------------------------
    def tap(self, X: np.ndarray) -> None:
        """Stash a deterministic stride-sample of one predict batch.
        Cost: one bounded row copy + a deque append.  Never locks,
        never bins, never touches the device."""
        k = self.sample_rows
        if k <= 0 or X.shape[0] == 0:
            return
        if self._num_feature is not None and \
                X.shape[1] != self._num_feature:
            # wrong-width request: the predictor fails it alone (HTTP
            # 400) — it must not poison the accumulator, where a mixed-
            # width concatenate would break every later scrape
            return
        n = int(X.shape[0])
        if n > k:
            step = -(-n // k)           # ceil: deterministic stride
            X = X[::step][:k]
        self._pending.append(np.array(X, np.float64))

    # -- scrape path ----------------------------------------------------
    def _absorb(self) -> None:
        """Drain pending samples into the accumulators.  All counting
        happens OUTSIDE the lock (pure local work on the drained
        batches); the lock only guards the final integer merges."""
        work: List[np.ndarray] = []
        while True:
            try:
                work.append(self._pending.popleft())
            except IndexError:
                break
        if not work:
            return
        # second line of defense behind tap's width check: only
        # same-width batches may concatenate
        width = (self._num_feature if self._num_feature is not None
                 else work[0].shape[1])
        work = [w for w in work if w.shape[1] == width]
        if not work:
            return
        Xs = work[0] if len(work) == 1 else np.concatenate(work, axis=0)
        counts: Dict[int, np.ndarray] = {}
        nan: Dict[int, int] = {}
        unseen: Dict[int, int] = {}
        for c in self.tracked:
            if c >= Xs.shape[1]:
                continue
            m = self._mappers[c]
            col = Xs[:, c]
            bins = m.values_to_bins(col)
            counts[c] = bin_occupancy(bins, self.profile
                                      .features[c]["num_bin"])
            nan[c] = int(np.isnan(col).sum())
            if int(m.bin_type) == 1:            # categorical: unseen =
                ok = np.isfinite(col)           # unmappable category
                iv = col[ok].astype(np.int64)
                seen = np.zeros(iv.shape, bool)
                for cat in m.categorical_2_bin:
                    if cat >= 0:
                        seen |= iv == cat
                unseen[c] = int((~seen).sum())
            else:
                unseen[c] = 0
        score_counts = None
        if self._score_fn is not None:
            s = np.asarray(self._score_fn(Xs), np.float64)
            if s.ndim == 1:
                s = s[None, :]
            score_counts = np.stack([
                score_hist_counts(self.profile.score_edges, row)
                for row in s[:self._score_counts.shape[0]]])
        with self._lock:
            self._rows += int(Xs.shape[0])
            for c, v in counts.items():
                self._counts[c] += v
                self._nan[c] += nan[c]
                self._unseen[c] += unseen[c]
            if score_counts is not None:
                self._score_counts[:score_counts.shape[0]] += score_counts

    def snapshot(self) -> Dict:
        """Absorb pending samples, compute every divergence (float64),
        publish the gauges, and fire the warn-threshold transition.
        The shape of this dict IS the ``GET /drift`` per-model schema."""
        self._absorb()
        with self._lock:
            rows = self._rows
            counts = {c: self._counts[c].copy() for c in self.tracked}
            nan = dict(self._nan)
            unseen = dict(self._unseen)
            score_counts = self._score_counts.copy()
        features: Dict[str, Dict] = {}
        psi_max = 0.0
        psi_argmax = ""
        for c in self.tracked:
            ref = self.profile.features[c]
            obs_cnt = counts[c]
            total = int(obs_cnt.sum())
            f_psi = psi(ref["cnt"], obs_cnt)
            f_js = js_divergence(ref["cnt"], obs_cnt)
            nan_rate = nan[c] / total if total else 0.0
            out = {
                "psi": f_psi, "js": f_js,
                "rows": total,
                "nan_rate": nan_rate,
                "nan_delta": nan_rate - ref["nan_frac"],
                "unseen_rate": (unseen[c] / total if total else 0.0),
            }
            features[ref["name"]] = out
            if f_psi > psi_max:
                psi_max = f_psi
                psi_argmax = ref["name"]
        score_js = [js_divergence(ref_row, obs_row)
                    for ref_row, obs_row in zip(self.profile.score_counts,
                                                score_counts)]
        score_js_max = max(score_js) if score_js else 0.0
        warn = psi_max >= self.psi_warn
        self._note_transition(warn, psi_max, psi_argmax)
        snap = {
            "model": self.model,
            "rows_sampled": int(rows),
            "sample_rows": self.sample_rows,
            "psi_warn": self.psi_warn,
            "psi_max": psi_max,
            "psi_max_feature": psi_argmax,
            "score_js": score_js,
            "score_js_max": score_js_max,
            "warn": bool(warn),
            "features": features,
        }
        self._publish(snap)
        return snap

    # -- side channels --------------------------------------------------
    def _note_transition(self, warn: bool, psi_max: float,
                         feature: str) -> None:
        """Flight-recorder + log + counter, once per below->above
        crossing (re-arms when PSI falls back under the threshold)."""
        fire = False
        with self._lock:
            if warn and not self._warned:
                self._warned = True
                self._warnings += 1
                fire = True
            elif not warn:
                self._warned = False
        if not fire:
            return
        from ..utils.log import Log
        from . import flightrecorder

        flightrecorder.note("drift", "psi_warn", model=self.model,
                            feature=feature, psi=round(psi_max, 6))
        Log.warning(
            f"serving drift: model {self.model!r} feature {feature!r} "
            f"PSI {psi_max:.4f} >= serving_drift_psi_warn "
            f"{self.psi_warn:g} — input distribution has moved off the "
            "training bins")
        if self._stats is not None:
            self._stats.count("drift_warnings")

    def _publish(self, snap: Dict) -> None:
        if self._stats is None:
            return
        for name, f in snap["features"].items():
            self._stats.set_drift_psi(self.model, name, f["psi"])
        self._stats.set_drift_score_js(self.model, snap["score_js_max"])
        self._stats.set_drift_rows(self.model, snap["rows_sampled"])
        # warn-threshold state as a gauge (ISSUE 17): 1 while PSI sits
        # at/above the threshold, 0 once it re-arms — the pollable twin
        # of the one-shot psi_warn flight-recorder event
        self._stats.set_drift_warn_active(self.model, snap["warn"])

    def warnings(self) -> int:
        with self._lock:
            return int(self._warnings)

    def warn_active(self) -> bool:
        """True while the last snapshot sat at/above the PSI warn
        threshold (the state the `lgbm_drift_warn_active` gauge
        mirrors) — what the continual controller polls as its drift
        trigger, without re-reading log text."""
        with self._lock:
            return bool(self._warned)


# ---------------------------------------------------------------------------
# offline comparison (model_report --compare-data)
# ---------------------------------------------------------------------------
def compare_dataset(profile: FeatureProfile, mappers: List,
                    X: np.ndarray,
                    score_fn: Optional[Callable] = None) -> Dict:
    """One-shot drift table of a raw matrix against a profile — the
    batch analog of a DriftMonitor scrape (same math, no sampling)."""
    mon = DriftMonitor(profile, mappers, sample_rows=max(X.shape[0], 1),
                       model="offline", score_fn=score_fn)
    mon.tap(np.asarray(X, np.float64))
    return mon.snapshot()
