"""CLI application: `python -m lightgbm_tpu task=train config=train.conf`.

Mirrors the reference CLI (reference src/main.cpp:11, src/application/
application.cpp:30-251): argv `key=value` pairs override the config file;
tasks are train / predict / refit / convert_model (if-else C++ codegen,
codegen.py).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

import numpy as np

from .config import Config


def parse_argv(argv: List[str]) -> Dict[str, str]:
    """argv key=value pairs + optional config file
    (reference application.cpp:30-81 LoadParameters)."""
    cli: Dict[str, str] = {}
    for arg in argv:
        if "=" in arg:
            k, v = arg.split("=", 1)
            cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    conf_key = next((k for k in ("config", "config_file") if k in cli), None)
    if conf_key:
        params.update(Config.load_conf_file(cli[conf_key]))
    params.update(cli)  # CLI overrides file values (application.cpp:62-66)
    return params


class Application:
    def __init__(self, argv: List[str]):
        self.raw_params = parse_argv(argv)
        self.config = Config(self.raw_params)

    def run(self) -> None:
        task = str(self.config.task).lower()
        if task == "train" or task == "training":
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "refit" or task == "refit_tree":
            self.refit()
        elif task == "convert_model":
            self.convert_model()
        elif task in ("serve", "serving"):
            self.serve()
        elif task == "continual":
            self.continual()
        else:
            raise ValueError(f"unknown task {task!r}")

    # ------------------------------------------------------------------
    def serve(self) -> None:
        """task=serve: load input_model into a serving registry and run
        the HTTP/JSON endpoint (lightgbm_tpu/serving) until ^C."""
        from .serving import ServingSession
        from .serving.server import serve_forever

        cfg = self.config
        if not cfg.input_model:
            raise ValueError("serve needs input_model=<file>")
        session = ServingSession(params=dict(self.raw_params))
        # CLI params reach the served booster too (tpu_predict_device,
        # tpu_predict_chunk_rows, predict_disable_shape_check, ...)
        key = session.load(str(cfg.serving_model_name),
                           model_file=str(cfg.input_model),
                           params=dict(self.raw_params))
        print(f"[lightgbm_tpu] serving {key} on "
              f"http://{cfg.serving_host}:{int(cfg.serving_port)} "
              "(POST /predict, POST /load, POST /drain, GET /stats, "
              "GET /models; SIGTERM drains)")
        serve_forever(session, str(cfg.serving_host), int(cfg.serving_port))

    # ------------------------------------------------------------------
    def continual(self) -> None:
        """task=continual: serve input_model over HTTP AND run the
        train-behind-serve loop (lightgbm_tpu/continual) against it —
        drift / row-count / cadence triggers retrain, the shadow gate
        promotes or refuses, `lgbm_continual_*` metrics ride the
        session's /metrics scrape.  An optional `data=<file>` labeled
        stream pre-feeds the ingest buffer (the offline stand-in for a
        production label join); production callers push labeled batches
        through `ContinualController.observe`."""
        from .continual import ContinualController
        from .serving import ServingSession
        from .serving.server import serve_http

        cfg = self.config
        if not cfg.input_model:
            raise ValueError("continual needs input_model=<file>")
        session = ServingSession(params=dict(self.raw_params))
        name = str(cfg.serving_model_name)
        session.load(name, model_file=str(cfg.input_model),
                     params=dict(self.raw_params))
        server = serve_http(session, str(cfg.serving_host),
                            int(cfg.serving_port))
        ctl = ContinualController(session, name,
                                  params=dict(self.raw_params))
        if cfg.data:
            from .io.parser import load_text_file

            X, y, _, _, _, _ = load_text_file(
                str(cfg.data), label_column=str(cfg.label_column or ""))
            chunk = max(int(cfg.tpu_ingest_chunk_rows), 1)
            for lo in range(0, len(X), chunk):
                ctl.observe(X[lo:lo + chunk], y[lo:lo + chunk])
            print(f"[lightgbm_tpu] continual buffer pre-fed "
                  f"{ctl.buffer.rows} labeled rows from {cfg.data}")
        port = server.server_address[1]
        print(f"[lightgbm_tpu] continual loop behind {name} on "
              f"http://{cfg.serving_host}:{port} — triggers: psi_warn"
              f" / {ctl.buffer.retain_rows} rows / "
              f"{float(cfg.tpu_continual_interval_s):g}s cadence; "
              "lgbm_continual_* on GET /metrics; ^C stops")
        try:
            ctl.run()
        except KeyboardInterrupt:  # pragma: no cover - operator stop
            pass
        finally:
            ctl.stop()
            server.shutdown()

    # ------------------------------------------------------------------
    def convert_model(self) -> None:
        """Model file -> standalone C++ source (reference
        application.cpp:222-229 ConvertModel + gbdt_model_text.cpp:87)."""
        from .booster import Booster
        from .codegen import model_to_cpp

        cfg = self.config
        if not cfg.input_model:
            raise ValueError("convert_model needs input_model=<file>")
        lang = str(cfg.convert_model_language).lower()
        if lang not in ("", "cpp", "c++"):
            raise ValueError(
                f"convert_model_language={lang!r}: only cpp is supported")
        bst = Booster(model_file=str(cfg.input_model))
        drv = bst._driver
        sigmoid = getattr(drv.objective, "sigmoid", 1.0)
        name = drv.objective.name if drv.objective is not None else ""
        src = model_to_cpp(drv.models, drv.num_tree_per_iteration, name,
                           sigmoid=float(sigmoid),
                           average_output=bool(drv.average_output))
        out = str(cfg.convert_model)
        with open(out, "w") as f:
            f.write(src)
        print(f"[lightgbm_tpu] model converted to C++ at {out}")

    # ------------------------------------------------------------------
    def train(self) -> None:
        from . import Dataset, train as train_fn
        cfg = self.config
        if not cfg.data:
            raise ValueError("no training data: set data=<file>")
        t0 = time.time()
        train_set = Dataset(cfg.data, params=dict(self.raw_params))
        train_set.construct()
        print(f"[lightgbm_tpu] finished loading data in "
              f"{time.time() - t0:.2f} seconds")

        valid_sets, valid_names = [], []
        if cfg.is_provide_training_metric:
            valid_sets.append(train_set)
            valid_names.append("training")
        for i, vf in enumerate(cfg.valid):
            vs = Dataset(vf, reference=train_set,
                         params=dict(self.raw_params))
            valid_sets.append(vs)
            valid_names.append(f"valid_{i + 1}")

        init_model = cfg.input_model if cfg.input_model else None
        booster = train_fn(
            dict(self.raw_params), train_set,
            num_boost_round=int(cfg.num_iterations),
            valid_sets=valid_sets, valid_names=valid_names,
            init_model=init_model,
            verbose_eval=(int(cfg.metric_freq)
                          if int(cfg.verbosity) > 0 else False))
        booster.save_model(cfg.output_model)
        print(f"[lightgbm_tpu] finished training; model saved to "
              f"{cfg.output_model}")

    # ------------------------------------------------------------------
    def refit(self) -> None:
        """task=refit: re-fit the input model's leaf values on `data`
        (reference Application::RefitTree, application.cpp:231-251)."""
        from . import Booster
        from .io.parser import load_text_file
        cfg = self.config
        if not cfg.data:
            raise ValueError("no refit data: set data=<file>")
        if not cfg.input_model:
            raise ValueError("no model file: set input_model=<file>")
        booster = Booster(model_file=cfg.input_model)
        X, y, _, _, _, _ = load_text_file(
            cfg.data, label_column=str(cfg.label_column or ""))
        new_booster = booster.refit(X, y,
                                    decay_rate=float(cfg.refit_decay_rate))
        new_booster.save_model(cfg.output_model)
        print(f"[lightgbm_tpu] finished refit, model saved to "
              f"{cfg.output_model}")

    def predict(self) -> None:
        from . import Booster
        cfg = self.config
        if not cfg.data:
            raise ValueError("no prediction data: set data=<file>")
        if not cfg.input_model:
            raise ValueError("no model file: set input_model=<file>")
        booster = Booster(model_file=cfg.input_model)
        result = booster.predict(
            cfg.data,
            num_iteration=(int(cfg.num_iteration_predict)
                           if int(cfg.num_iteration_predict) > 0 else None),
            raw_score=bool(cfg.predict_raw_score),
            pred_leaf=bool(cfg.predict_leaf_index),
            pred_contrib=bool(cfg.predict_contrib),
            pred_early_stop=bool(cfg.pred_early_stop),
            pred_early_stop_freq=int(cfg.pred_early_stop_freq),
            pred_early_stop_margin=float(cfg.pred_early_stop_margin),
            predict_disable_shape_check=bool(cfg.predict_disable_shape_check))
        out = np.asarray(result)
        with open(cfg.output_result, "w") as f:
            if out.ndim == 1:
                for v in out:
                    f.write(f"{v:g}\n")
            else:
                for row in out:
                    f.write("\t".join(f"{v:g}" for v in row) + "\n")
        print(f"[lightgbm_tpu] finished prediction; results saved to "
              f"{cfg.output_result}")


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m lightgbm_tpu task=train config=train.conf "
              "[key=value ...]\n"
              "       python -m lightgbm_tpu serve input_model=model.txt "
              "[serving_port=18080 ...]")
        return 1
    # `python -m lightgbm_tpu serve ...` sugar for task=serve
    if argv[0] in ("serve", "serving"):
        argv = ["task=serve"] + list(argv[1:])
    # `python -m lightgbm_tpu continual ...` sugar for task=continual
    elif argv[0] == "continual":
        argv = ["task=continual"] + list(argv[1:])
    Application(argv).run()
    return 0
