"""User-facing Dataset / Booster (reference python-package/lightgbm/basic.py).

`Dataset` wraps lazy binned-data construction; `Booster` wraps the boosting
driver.  Unlike the reference there is no ctypes boundary — the "C API" level
is `lightgbm_tpu.models` directly — but the surface mirrors basic.py so user
code ports over unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .io.dataset import TrainingData, Metadata, _is_scipy_sparse
from .utils.log import LightGBMError  # noqa: F401 (reference basic.py export)


class Dataset:
    """Lazily-constructed binned dataset (reference basic.py:712-1040)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, silent: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._inner: Optional[TrainingData] = None
        self.used_indices: Optional[np.ndarray] = None
        # per-categorical-column category tables captured from a pandas
        # train frame (None = data was not pandas / had no category cols)
        self.pandas_categorical: Optional[List[List]] = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._inner is not None:
            return self
        cfg = Config(self.params)
        if bool(cfg.two_round) and not isinstance(self.data, str):
            from .utils.log import Log

            # two_round is a FILE-loading strategy (sampled bin-finding
            # then a streaming second pass); in-memory matrices are
            # already resident, so there is nothing to stream
            Log.warning("two_round=true ignored for in-memory data")
        ref_inner = self.reference._inner if self.reference is not None else None
        if self.reference is not None and ref_inner is None:
            self.reference.construct()
            ref_inner = self.reference._inner

        if isinstance(self.data, str):
            if ref_inner is not None:
                self._inner = TrainingData.from_file(self.data, cfg, reference=ref_inner)
            else:
                self._inner = TrainingData.from_file(self.data, cfg)
            if self.label is not None:
                self._inner.metadata.set_field("label", self.label)
        else:
            feature_names = None if self.feature_name == "auto" else list(self.feature_name)
            pd_cat_idx: Sequence[int] = []
            if _is_pandas_df(self.data):
                # valid sets re-use the train frame's category tables so
                # codes line up with the reference dataset's bins
                ref_pc = (self.reference.pandas_categorical
                          if self.reference is not None else None)
                X, pd_names, pd_cat_idx, self.pandas_categorical = \
                    _pandas_to_matrix(self.data, ref_pc)
                if feature_names is None:
                    feature_names = pd_names
            else:
                X = self.data
                if not _is_scipy_sparse(X):
                    X = _to_2d_array(X)
            cat: Sequence[int] = []
            if isinstance(self.categorical_feature, (list, tuple)):
                if all(isinstance(c, (int, np.integer)) for c in self.categorical_feature):
                    cat = [int(c) for c in self.categorical_feature]
                elif feature_names:
                    cat = [feature_names.index(c) for c in self.categorical_feature]
            # pandas category-dtype columns are categorical regardless of
            # the (default "auto") categorical_feature setting
            cat = sorted(set(cat) | set(pd_cat_idx))
            # sparse input bins in O(nnz) without the [n, F] f64 blow-up
            factory = (TrainingData.from_sparse if _is_scipy_sparse(X)
                       else TrainingData.from_matrix)
            self._inner = factory(
                X, None if self.label is None else np.asarray(self.label),
                cfg, weight=self.weight, group_sizes=self.group,
                init_score=self.init_score, reference=ref_inner,
                feature_names=feature_names, categorical_features=cat)
        if self.group is not None and self._inner.metadata.query_boundaries is None:
            self._inner.metadata.set_field("group", np.asarray(self.group))
        if self.weight is not None and self._inner.metadata.weight is None:
            self._inner.metadata.set_field("weight", np.asarray(self.weight))
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params if params is not None else self.params)

    def save_binary(self, filename: str) -> "Dataset":
        """Serialize the constructed binned dataset (reference
        Dataset::SaveBinaryFile via LGBM_DatasetSaveBinary); reloading a
        `<data>.bin` path skips parsing and bin finding."""
        self.construct()
        self._inner.save_binary(filename)
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Reference basic.py:1279: must be called before construction —
        bin types are fixed at bin-finding time."""
        if self._inner is not None \
                and list(categorical_feature) != list(
                    self.categorical_feature or []):
            raise RuntimeError(
                "cannot change categorical_feature after the dataset is "
                "constructed")
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Reference basic.py:1327: align this (unconstructed) dataset's
        bins with `reference`'s mappers."""
        if self._inner is not None and self.reference is not reference:
            raise RuntimeError(
                "cannot change reference after the dataset is constructed")
        self.reference = reference
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        """Reference basic.py:1353."""
        if feature_name == "auto":
            self.feature_name = feature_name
            return self
        names = list(feature_name)  # materialize ONCE (generators)
        if self._inner is not None:
            if len(names) != self._inner.num_total_features:
                raise ValueError(
                    f"{len(names)} names for "
                    f"{self._inner.num_total_features} features")
            self._inner.feature_names = list(names)
        self.feature_name = names
        return self

    def set_field(self, name: str, data) -> "Dataset":
        self.construct()
        self._inner.metadata.set_field(name, data)
        return self

    def get_field(self, name: str):
        self.construct()
        return self._inner.metadata.get_field(name)

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._inner is not None:
            self._inner.metadata.set_field("label", label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._inner is not None:
            self._inner.metadata.set_field("weight", weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._inner is not None:
            self._inner.metadata.set_field("group", group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._inner is not None:
            self._inner.metadata.set_field("init_score", init_score)
        return self

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        b = self.get_field("group")
        return None if b is None else np.diff(b)

    def get_init_score(self):
        return self.get_field("init_score")

    def get_data(self):
        """The raw data this dataset was built from (reference basic.py
        get_data).  Subsets built with subset() slice the parent's raw
        rows by used_indices — composing indices through subset-of-subset
        chains — and a freed chain raises, as the reference does."""
        if self.data is not None or getattr(self, "used_indices", None) is None:
            if self.data is None and self._inner is not None:
                raise LightGBMError(
                    "Cannot call `get_data` after freed raw data, "
                    "set free_raw_data=False when construct Dataset to "
                    "avoid this.")
            return self.data
        # walk the reference chain, composing used_indices, until a
        # parent still holding raw rows is found
        idx = np.asarray(self.used_indices)
        parent = self.reference
        while parent is not None and parent.data is None \
                and getattr(parent, "used_indices", None) is not None \
                and parent.reference is not None:
            idx = np.asarray(parent.used_indices)[idx]
            parent = parent.reference
        if parent is None or parent.data is None:
            raise LightGBMError(
                "Cannot call `get_data` after freed raw data, "
                "set free_raw_data=False when construct Dataset to "
                "avoid this.")
        pdata = parent.data
        if _is_pandas_df(pdata):
            return pdata.iloc[idx]
        if isinstance(pdata, (list, tuple)):
            pdata = _to_2d_array(pdata)
        return pdata[idx]

    def get_feature_penalty(self):
        """Per-used-feature split penalty array, or None (reference
        basic.py get_feature_penalty)."""
        self.construct()
        return self._inner.feature_penalty

    def get_monotone_constraints(self):
        """Per-used-feature monotone constraint array, or None (reference
        basic.py get_monotone_constraints)."""
        self.construct()
        return self._inner.monotone_constraints

    def get_ref_chain(self, ref_limit: int = 100):
        """The set of datasets reachable through `reference` links
        (reference basic.py get_ref_chain)."""
        head = self
        ref_chain = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(head)
                if head.reference is not None and head.reference not in ref_chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def num_data(self) -> int:
        self.construct()
        return self._inner.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._inner.num_total_features

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Merge `other`'s features into this dataset column-wise
        (reference Dataset::AddFeaturesFrom, c_api.h:297 /
        python-package Dataset.add_features_from): both datasets are
        constructed, must hold the same number of rows, and `other`'s
        binned columns, mappers, names and per-feature metadata are
        appended after this dataset's."""
        self.construct()
        other.construct()
        ia, ib = self._inner, other._inner
        if ia.num_data != ib.num_data:
            raise ValueError("datasets have different row counts")
        na = ia.num_total_features
        n_used_a = len(ia.used_feature_idx)
        n_used_b = len(ib.used_feature_idx)
        ia.bins = np.concatenate([ia.bins, ib.bins], axis=1)
        ia.used_feature_idx = list(ia.used_feature_idx) + \
            [na + c for c in ib.used_feature_idx]
        ia.mappers = list(ia.mappers) + list(ib.mappers)
        ia.feature_names = list(ia.feature_names) + list(ib.feature_names)
        ia.num_total_features = na + ib.num_total_features

        def _merge_per_used(attr, dtype, fill):
            va, vb = getattr(ia, attr), getattr(ib, attr)
            if va is None and vb is None:
                return
            if va is None:
                va = np.full(n_used_a, fill, dtype)
            if vb is None:
                vb = np.full(n_used_b, fill, dtype)
            setattr(ia, attr, np.concatenate([va, vb]))

        _merge_per_used("monotone_constraints", np.int32, 0)
        _merge_per_used("feature_penalty", np.float32, 1.0)
        # pandas category tables are keyed by category-column order of
        # appearance; self's columns all precede other's, so the merged
        # table list is the concatenation (mirrors subset()'s propagation)
        if self.pandas_categorical or other.pandas_categorical:
            self.pandas_categorical = ((self.pandas_categorical or [])
                                       + (other.pandas_categorical or []))
        ia._device_bins = None
        return self

    def subset(self, used_indices, params=None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers (for cv / bagging)."""
        self.construct()
        idx = np.asarray(used_indices)
        sub = Dataset.__new__(Dataset)
        sub.data = None
        sub.label = None
        sub.reference = self
        sub.weight = None
        sub.group = None
        sub.init_score = None
        sub.feature_name = self.feature_name
        sub.categorical_feature = self.categorical_feature
        sub.pandas_categorical = self.pandas_categorical
        sub.params = dict(params) if params else dict(self.params)
        sub.free_raw_data = True
        sub.used_indices = idx
        inner = TrainingData()
        src = self._inner
        inner.num_data = len(idx)
        inner.num_total_features = src.num_total_features
        inner.used_feature_idx = list(src.used_feature_idx)
        inner.mappers = src.mappers
        inner.bins = src.bins[idx]
        inner.feature_names = src.feature_names
        inner.config = src.config
        inner.monotone_constraints = src.monotone_constraints
        inner.feature_penalty = src.feature_penalty
        md = src.metadata
        group_sizes = None
        if md.query_boundaries is not None:
            # rows of one query must be taken together (cv folds do this);
            # recover per-query sizes by run-length over query ids
            qid = np.searchsorted(md.query_boundaries, idx, side="right") - 1
            if np.any(np.diff(qid) < 0):
                raise ValueError("subset indices must be sorted for grouped data")
            change = np.flatnonzero(np.diff(qid)) + 1
            starts = np.concatenate([[0], change, [len(idx)]])
            group_sizes = np.diff(starts)
        inner.metadata = Metadata(
            len(idx), md.label[idx],
            None if md.weight is None else md.weight[idx],
            group_sizes,
            None if md.init_score is None else _subset_init_score(md, idx))
        sub._inner = inner
        return sub


def _subset_init_score(md: Metadata, idx: np.ndarray):
    s = md.init_score
    if s is None:
        return None
    if s.ndim == 1 and len(s) == md.num_data:
        return s[idx]
    return s.reshape(md.num_data, -1)[idx].reshape(-1)


def _is_pandas_df(data) -> bool:
    import sys

    pd = sys.modules.get("pandas")
    return pd is not None and isinstance(data, pd.DataFrame)


def _pandas_to_matrix(df, pandas_categorical=None, training=True):
    """DataFrame -> (X float64, feature_names, cat_idx, pandas_categorical).

    Columns with pandas `category` dtype become their integer codes
    (missing/unseen -> -1, which the categorical bin path routes like NaN).
    At training time (pandas_categorical=None) the observed category lists
    are captured per categorical column; at prediction time the stored
    lists re-index the incoming values so codes line up with training even
    when the new frame's categories differ in order or content.  This is
    the role of the reference package's pandas ingestion
    (reference python-package/lightgbm/basic.py:313-354), re-derived.
    """
    from pandas.api.types import is_numeric_dtype

    names = [str(c) for c in df.columns]
    cat_cols = [i for i, c in enumerate(df.columns)
                if str(df.dtypes.iloc[i]) == "category"]
    bad = [f"{names[i]} ({df.dtypes.iloc[i]})" for i in range(len(names))
           if i not in cat_cols and not is_numeric_dtype(df.iloc[:, i])]
    if bad:
        raise ValueError(
            f"DataFrame columns [{', '.join(bad)}] have non-numeric "
            "(object/string/...) dtype; cast them to 'category' or "
            "numeric before constructing a Dataset")
    if pandas_categorical is None:
        if cat_cols and not training:
            raise ValueError(
                "this model has no stored pandas category tables "
                "(trained on non-pandas data or an old model file); "
                "cannot map the DataFrame's category-dtype columns "
                f"{[names[i] for i in cat_cols]} onto trained bins — "
                "pass integer codes instead")
        pandas_categorical = [list(df.iloc[:, i].cat.categories)
                              for i in cat_cols]
    elif len(pandas_categorical) != len(cat_cols):
        raise ValueError(
            f"train/predict DataFrames disagree on categorical columns: "
            f"model has {len(pandas_categorical)}, data has {len(cat_cols)}")
    X = np.empty((len(df), len(names)), dtype=np.float64)
    ci = 0
    for i in range(len(names)):
        col = df.iloc[:, i]
        if i in cat_cols:
            cats = pandas_categorical[ci]
            ci += 1
            if list(col.cat.categories) != cats:
                # re-index onto the training category table BY VALUE
                # (codes follow the stored order; unseen values -> -1)
                col = col.cat.set_categories(cats)
            X[:, i] = col.cat.codes.to_numpy(dtype=np.float64)
        else:
            X[:, i] = col.to_numpy(dtype=np.float64, na_value=np.nan)
    return X, names, cat_cols, pandas_categorical


def _to_2d_array(data, pandas_categorical=None) -> np.ndarray:
    # prediction-side conversion: category columns need the stored tables
    if _is_pandas_df(data):
        return _pandas_to_matrix(data, pandas_categorical,
                                 training=False)[0]
    if hasattr(data, "toarray"):  # scipy sparse
        return np.asarray(data.toarray(), dtype=np.float64)
    if isinstance(data, (list, tuple)) and data and all(
            isinstance(c, np.ndarray) and c.ndim == 2 for c in data):
        # reference basic.py accepts a list of 2-D ndarray row chunks;
        # DataFrame/sparse chunks deliberately fall through (categorical
        # code mapping and densification only exist for whole objects)
        return np.concatenate(list(data), axis=0, dtype=np.float64)
    return np.asarray(data, dtype=np.float64)


from .booster import Booster  # noqa: E402  (re-export; keeps basic.py the facade)
