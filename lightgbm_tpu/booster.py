"""Booster: the training/prediction handle (reference basic.py Booster class).

Wraps the boosting driver in `lightgbm_tpu.models` the way the reference
Booster wraps the C API handle (reference python-package/lightgbm/basic.py,
src/c_api.cpp:98-320).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .config import Config


def _split_pandas_categorical(text: str):
    """Split a model string into (model_text, pandas_categorical).

    The Python layer appends one `pandas_categorical:<json>` line to saved
    models (the reference package does the same at the end of its files,
    python-package/lightgbm/basic.py _dump_pandas_categorical), so both
    packages' files interchange."""
    import json

    marker = "\npandas_categorical:"
    pos = text.rfind(marker)
    if pos < 0:
        return text, None
    payload = text[pos + len(marker):].split("\n", 1)[0].strip()
    try:
        pc = json.loads(payload) if payload else None
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"corrupt pandas_categorical line in model: {payload[:80]!r}"
        ) from exc
    return text[:pos] + "\n", pc


class Booster:
    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional["Dataset"] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        from .basic import Dataset
        from .models import create_boosting
        from .models.gbdt import GBDT

        self.params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._valid_names: List[str] = []
        self._train_set: Optional[Dataset] = None
        self._driver = None
        self.pandas_categorical = None
        self._attr: Dict[str, str] = {}

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("train_set must be a Dataset")
            if train_set._inner is None:
                # merge training params into dataset params before lazy
                # construction (reference basic.py _update_params): dataset-
                # affecting keys like max_bin / monotone_constraints may be
                # given at train() level
                train_set.params = {**train_set.params, **self.params}
            train_set.construct()
            self._train_set = train_set
            cfg = Config(self.params)
            self._driver = create_boosting(cfg)
            self._driver.init(cfg, train_set._inner)
            self.pandas_categorical = train_set.pandas_categorical
        elif model_file is not None:
            with open(model_file) as f:
                text = f.read()
            text, self.pandas_categorical = _split_pandas_categorical(text)
            self._driver = GBDT.from_model_string(text)
            self.params = dict(self._driver.loaded_params)
        elif model_str is not None:
            model_str, self.pandas_categorical = \
                _split_pandas_categorical(model_str)
            self._driver = GBDT.from_model_string(model_str)
            self.params = dict(self._driver.loaded_params)
        else:
            raise ValueError("need train_set, model_file or model_str")
        if train_set is None and params:
            # reference basic.py merges user-supplied params over the
            # loaded model's stored ones, so introspection reflects them
            self.params.update(params)
            # loaded-model boosters skip GBDT.init (which applies the cap
            # on the train path), so honor the USER-supplied num_threads
            # (and aliases, via Config) here
            n_threads = int(Config(dict(params)).num_threads)
            if n_threads > 0:
                from .native import set_num_threads

                set_num_threads(n_threads)

    # -- copy / pickling (reference basic.py Booster round-trips its
    # C handle through the model string; the driver plays that role) ----
    def __copy__(self) -> "Booster":
        return self.__deepcopy__(None)

    def __deepcopy__(self, _memo) -> "Booster":
        out = Booster(model_str=self.model_to_string(num_iteration=-1))
        out.params = dict(self.params)
        out.best_iteration = self.best_iteration
        out._attr = dict(self._attr)
        return out

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_train_set", None)
        state.pop("_driver", None)
        state["_model_str"] = self.model_to_string(num_iteration=-1)
        return state

    def __setstate__(self, state):
        from .models.gbdt import GBDT

        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        self._train_set = None
        self._driver = None
        if model_str is not None:
            model_str, pc = _split_pandas_categorical(model_str)
            self._driver = GBDT.from_model_string(model_str)
            if self.pandas_categorical is None:
                self.pandas_categorical = pc

    # -- attributes (reference basic.py Booster.attr/set_attr) ---------
    def attr(self, key: str) -> Optional[str]:
        return self._attr.get(key)

    def set_attr(self, **kwargs) -> "Booster":
        for key, value in kwargs.items():
            if value is None:
                self._attr.pop(key, None)
            elif isinstance(value, str):
                self._attr[key] = value
            else:
                raise ValueError("Only string values are accepted")
        return self

    # ------------------------------------------------------------------
    def add_valid(self, data, name: str) -> "Booster":
        data.construct()
        self._driver.add_valid(data._inner, name)
        self._valid_names.append(name)
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if no further splits occurred."""
        if fobj is None:
            return self._driver.train_one_iter()
        grad, hess = fobj(self._driver.current_score_for_fobj(), self._train_set)
        return self._driver.train_one_iter_custom(np.asarray(grad, np.float32),
                                                  np.asarray(hess, np.float32))

    def rollback_one_iter(self) -> "Booster":
        self._driver.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        # a METHOD, not a property — reference basic.py Booster API
        return self._driver.current_iteration()

    def num_trees(self) -> int:
        return self._driver.num_total_model()

    def num_model_per_iteration(self) -> int:
        return self._driver.num_model_per_iteration()

    def eval_train(self, feval=None) -> List[Tuple]:
        return self._driver.eval("training", -1, feval=feval,
                                 booster=self)

    def eval_valid(self, feval=None) -> List[Tuple]:
        out: List[Tuple] = []
        for i, name in enumerate(self._valid_names):
            out.extend(self._driver.eval(name, i, feval=feval, booster=self))
        return out

    def eval(self, data, name: str, feval=None) -> List[Tuple]:
        data.construct()
        return self._driver.eval_for_data(data._inner, name, feval=feval)

    def _device_predict_requested(self, kwargs,
                                  for_dataset: bool = False) -> bool:
        """Route this predict through the jitted bin-space forest
        predictor?  `device='tpu'` (kwarg, or the stored device_type)
        selects it, modulated by tpu_predict_device: `true` forces it,
        `false` pins the native walker, `auto` (default) uses it only
        when the default jax backend is an actual TPU — on CPU hosts the
        native OMP walker stays faster for one-shot predicts.
        Pre-binned Dataset input (`for_dataset`) has NO native
        alternative, so auto mode accepts it on every backend."""
        # raw param reads (alias-aware), not a full Config build: this
        # runs on EVERY predict call and only needs two values
        from .config import parse_tristate

        raw_dev = self.params.get("device_type",
                                  self.params.get("device", "tpu"))
        dev = str(kwargs.get("device", raw_dev)).strip().lower()
        if dev != "tpu":
            return False
        # kwargs override the stored mode (serving pins the device path
        # per call without mutating the booster's own params)
        mode = parse_tristate(kwargs.get(
            "tpu_predict_device",
            self.params.get("tpu_predict_device", "auto")))
        if mode == "true":
            return True
        if mode == "false":
            return False
        if for_dataset:
            return True
        import jax

        try:
            return jax.default_backend() == "tpu"
        except Exception:
            return False

    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        from .basic import Dataset, _to_2d_array
        if isinstance(data, Dataset):
            # pre-binned device predict: a constructed Dataset sharing the
            # training mappers skips the host binning pass entirely
            if pred_leaf or pred_contrib or kwargs.get("pred_early_stop"):
                raise ValueError("pred_leaf/pred_contrib/pred_early_stop "
                                 "need raw data, not a Dataset (they run "
                                 "on the native walker)")
            if not self._device_predict_requested(kwargs, for_dataset=True):
                raise TypeError(
                    "Cannot use Dataset instance for prediction on the "
                    "native path; pass raw data, or enable the device "
                    "predictor (device='tpu' with tpu_predict_device "
                    "not 'false')")
            data.construct()
            if num_iteration is None:
                num_iteration = (self.best_iteration
                                 if self.best_iteration >= 0 else -1)
            return self._driver.predict_binned_device(
                data._inner, num_iteration=num_iteration,
                raw_score=raw_score)
        if isinstance(data, str):
            from .io.parser import load_text_file
            cfg = Config(self.params)
            X = load_text_file(data, label_column=cfg.label_column,
                               header=True if cfg.header else None)[0]
            # file without a label column: reload keeping all columns
            if X.shape[1] == self.num_feature() - 1:
                X = load_text_file(data, label_column="", header=None)[0]
        else:
            from .io.dataset import _is_scipy_sparse

            if _is_scipy_sparse(data):
                # densify in bounded row chunks for the native walker —
                # never the whole [n, F] f64 (reference PredictForCSR
                # walks rows sparse; chunking keeps peak memory O(chunk))
                return self._predict_sparse_chunked(
                    data, num_iteration, raw_score, pred_leaf, pred_contrib,
                    kwargs)
            X = _to_2d_array(data, self.pandas_categorical)
        n_feat = self.num_feature()
        if X.shape[1] != n_feat:
            self._check_predict_shape(X.shape[1], kwargs)
            if X.shape[1] < n_feat:
                # absent trailing features predict as missing, like the
                # reference C predictor reading past ncol
                pad = np.full((X.shape[0], n_feat - X.shape[1]), np.nan)
                X = np.concatenate([np.asarray(X, np.float64), pad], axis=1)
            else:
                X = np.asarray(X, np.float64)[:, :n_feat]
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration >= 0 else -1
        return self._driver.predict(
            X, num_iteration=num_iteration, raw_score=raw_score,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib,
            pred_early_stop=bool(kwargs.get("pred_early_stop", False)),
            pred_early_stop_freq=int(kwargs.get("pred_early_stop_freq", 10)),
            pred_early_stop_margin=float(
                kwargs.get("pred_early_stop_margin", 10.0)),
            device_predict=self._device_predict_requested(kwargs))

    def _check_predict_shape(self, ncols: int, kwargs) -> None:
        """Raise on a predict feature-count mismatch unless
        predict_disable_shape_check (kwargs over stored params) is set —
        reference Parameters.rst semantics, string values accepted."""
        from .config import _parse_bool

        if _parse_bool(kwargs.get(
                "predict_disable_shape_check",
                Config(self.params).predict_disable_shape_check)):
            return
        from .utils.log import LightGBMError

        raise LightGBMError(
            f"The number of features in data ({ncols}) is not the same as "
            f"it was in training data ({self.num_feature()}).\n"
            "You can set ``predict_disable_shape_check=true`` to discard "
            "this error, but please be aware what you are doing.")

    def _predict_sparse_chunked(self, data, num_iteration, raw_score,
                                pred_leaf, pred_contrib, kwargs,
                                chunk_rows: int = 65536) -> np.ndarray:
        """Predict a scipy sparse matrix in dense row chunks.

        Every driver output is n-first ([n], [n, k], [n, T], [n, k*(F+1)])
        so chunks concatenate on axis 0; peak host memory is one
        [chunk_rows, F] f64 block instead of the full densified matrix."""
        n_feat = self.num_feature()
        if data.shape[1] != n_feat:
            self._check_predict_shape(data.shape[1], kwargs)
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration >= 0 else -1
        device_predict = self._device_predict_requested(kwargs)
        Xr = data.tocsr()
        if Xr.shape[1] > n_feat:
            # drop extra columns while still sparse (O(nnz)) — densifying
            # at full width would defeat the bounded-memory chunking
            Xr = Xr[:, :n_feat]
        outs = []
        for lo in range(0, max(Xr.shape[0], 1), chunk_rows):
            chunk = np.asarray(
                Xr[lo:lo + chunk_rows].todense(), dtype=np.float64)
            if chunk.shape[1] < n_feat:
                pad = np.full((chunk.shape[0], n_feat - chunk.shape[1]),
                              np.nan)
                chunk = np.concatenate([chunk, pad], axis=1)
            outs.append(self._driver.predict(
                chunk, num_iteration=num_iteration, raw_score=raw_score,
                pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                pred_early_stop=bool(kwargs.get("pred_early_stop", False)),
                pred_early_stop_freq=int(kwargs.get("pred_early_stop_freq",
                                                    10)),
                pred_early_stop_margin=float(
                    kwargs.get("pred_early_stop_margin", 10.0)),
                device_predict=device_predict))
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def model_from_string(self, model_str: str, verbose: bool = True
                          ) -> "Booster":
        """Replace this Booster's model in place from a model string
        (reference basic.py Booster.model_from_string)."""
        from .models.gbdt import GBDT

        model_str, self.pandas_categorical = \
            _split_pandas_categorical(model_str)
        self._driver = GBDT.from_model_string(model_str)
        self.params = dict(self._driver.loaded_params)
        self._train_set = None
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Value of one leaf (reference Booster.get_leaf_output ->
        LGBM_BoosterGetLeafValue)."""
        self._driver._materialize()
        return float(self._driver.models[tree_id].leaf_value[leaf_id])

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of this feature's used split thresholds across all
        trees (reference basic.py Booster.get_split_value_histogram)."""
        model = self.dump_model()
        feature_names = model["feature_names"]

        def want(split_feature) -> bool:
            if isinstance(feature, str):
                return (feature_names is not None
                        and feature_names[split_feature] == feature)
            return split_feature == feature

        values: List[float] = []

        def walk(node):
            if "split_index" in node:
                if want(node["split_feature"]):
                    if node["decision_type"] == "==":
                        raise ValueError(
                            "cannot compute a split value histogram for a "
                            "categorical feature")
                    values.append(float(node["threshold"]))
                walk(node["left_child"])
                walk(node["right_child"])

        for t in model["tree_info"]:
            walk(t["tree_structure"])
        if bins is None or (isinstance(bins, int)
                            and bins > len(set(values))
                            and xgboost_style):
            bins = max(len(set(values)), 1)
        hist, edges = np.histogram(values, bins=bins)
        if not xgboost_style:
            return hist, edges
        mask = hist != 0
        out = np.column_stack([edges[1:][mask], hist[mask]])
        try:
            import pandas as pd

            return pd.DataFrame(out, columns=["SplitValue", "Count"])
        except ImportError:
            return out

    def trees_to_dataframe(self):
        """All trees' nodes as one pandas DataFrame (reference basic.py
        Booster.trees_to_dataframe; same column contract)."""
        import pandas as pd

        if self.num_trees() == 0:
            raise ValueError("no trees to parse")
        model = self.dump_model()
        feature_names = model["feature_names"]
        rows: List[Dict[str, Any]] = []

        def node_index(node, ti):
            if "split_index" in node:
                return f"{ti}-S{node['split_index']}"
            return f"{ti}-L{node.get('leaf_index', 0)}"

        def walk(node, ti, depth, parent):
            is_split = "split_index" in node
            row = {
                "tree_index": ti,
                "node_depth": depth,
                "node_index": node_index(node, ti),
                "left_child": None,
                "right_child": None,
                "parent_index": parent,
                "split_feature": None,
                "split_gain": None,
                "threshold": None,
                "decision_type": None,
                "missing_direction": None,
                "missing_type": None,
                "value": None,
                "weight": None,
                "count": None,
            }
            if is_split:
                f = node["split_feature"]
                row.update(
                    left_child=node_index(node["left_child"], ti),
                    right_child=node_index(node["right_child"], ti),
                    split_feature=(feature_names[f] if feature_names
                                   else f),
                    split_gain=node["split_gain"],
                    threshold=node["threshold"],
                    decision_type=node["decision_type"],
                    missing_direction=("left" if node["default_left"]
                                       else "right"),
                    missing_type=node["missing_type"],
                    value=node["internal_value"],
                    weight=node["internal_weight"],
                    count=node["internal_count"])
            else:
                row.update(value=node["leaf_value"],
                           weight=node.get("leaf_weight"),
                           count=node.get("leaf_count"))
            rows.append(row)
            if is_split:
                me = row["node_index"]
                walk(node["left_child"], ti, depth + 1, me)
                walk(node["right_child"], ti, depth + 1, me)

        for t in model["tree_info"]:
            walk(t["tree_structure"], t["tree_index"], 1, None)
        return pd.DataFrame(rows)

    def refit(self, data, label, decay_rate: float = 0.9) -> "Booster":
        """New Booster with every tree's leaf values re-fit on `data`
        (reference basic.py Booster.refit -> GBDT::RefitTree)."""
        from .basic import _to_2d_array
        from .config import Config

        X = _to_2d_array(data, self.pandas_categorical)
        out = Booster(model_str=self._driver.save_model_to_string())
        out.params = dict(self.params)
        out.pandas_categorical = self.pandas_categorical
        out._driver.refit(X, np.asarray(label), decay_rate,
                          config=Config(self.params) if self.params else None)
        return out

    # -- fault tolerance (utils/checkpoint.py) -------------------------
    def save_checkpoint(self, directory: str, keep: int = 3) -> str:
        """Write one atomic training checkpoint (model + PRNG streams +
        score buffers) into `directory`; returns the checkpoint path.
        In a jax.distributed group every host writes its local bundle
        and rank 0 commits the global topology manifest after the
        all-hosts-durable barrier.  `lgb.train` does this automatically
        when `tpu_checkpoint_dir` is configured."""
        from .utils.checkpoint import make_manager, save_checkpoint

        return save_checkpoint(self, make_manager(directory, keep=keep))

    def resume_from_checkpoint(self, directory: str) -> Optional[int]:
        """Restore this (freshly-constructed, same training data)
        booster from the newest valid checkpoint in `directory`;
        returns the restored iteration, or None when no valid
        checkpoint exists.  The shard/host topology may DIFFER from the
        checkpointed run's (elastic resume): global score buffers are
        re-sharded onto the live mesh, and continued int8/int16
        training stays bit-identical to a never-interrupted run.  A
        material params mismatch names the differing keys (warning, or
        error under `tpu_resume_strict`)."""
        from .utils.checkpoint import make_manager, restore_checkpoint

        state = restore_checkpoint(self, make_manager(directory))
        return None if state is None else int(state["iteration"])

    # -- model IO ------------------------------------------------------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration >= 0 else -1
        with open(filename, "w") as f:
            f.write(self._driver.save_model_to_string(
                num_iteration=num_iteration, start_iteration=start_iteration))
            f.write(self._pandas_categorical_line())
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration >= 0 else -1
        return (self._driver.save_model_to_string(
            num_iteration=num_iteration, start_iteration=start_iteration)
            + self._pandas_categorical_line())

    def _pandas_categorical_line(self) -> str:
        import json

        def np_default(o):
            if isinstance(o, np.integer):
                return int(o)
            if isinstance(o, np.floating):
                return float(o)
            if isinstance(o, np.bool_):
                return bool(o)
            # a str() fallback would save a table whose values no longer
            # match the frame's at predict time (everything -> missing);
            # fail at save time instead
            raise TypeError(
                f"cannot persist pandas category value {o!r} "
                f"({type(o).__name__}); use str/int/float categories")

        return ("\npandas_categorical:"
                + json.dumps(self.pandas_categorical, default=np_default)
                + "\n")

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict:
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration >= 0 else -1
        return self._driver.dump_model(num_iteration=num_iteration,
                                       start_iteration=start_iteration)

    # -- introspection -------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        return self._driver.feature_importance(importance_type)

    def feature_name(self) -> List[str]:
        return list(self._driver.feature_names)

    def num_feature(self) -> int:
        return self._driver.max_feature_idx + 1

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self._driver.reset_config(Config(self.params))
        return self

    def set_network(self, machines: str, local_listen_port: int = 12400,
                    listen_time_out: int = 120, num_machines: int = 1
                    ) -> "Booster":
        """Join the multi-host training mesh (reference basic.py
        Booster.set_network -> LGBM_NetworkInit; here the machine list maps
        onto jax.distributed, parallel/mesh.py init_multihost).

        listen_time_out is accepted for signature parity; rendezvous
        timeouts are governed by jax.distributed itself."""
        from .parallel.mesh import init_multihost

        init_multihost(machines, int(local_listen_port), int(num_machines))
        self.params.update({"machines": machines,
                            "local_listen_port": int(local_listen_port),
                            "num_machines": int(num_machines)})
        self._network_set = True
        return self

    def free_network(self) -> "Booster":
        """Reference Booster.free_network analog: forget the network params
        (the jax.distributed runtime itself stays up for the process)."""
        for k in ("machines", "local_listen_port", "num_machines"):
            self.params.pop(k, None)
        self._network_set = False
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        """Drop the training/validation data (reference basic.py:1808):
        the trained model stays usable for predict/save/dump, but further
        update()/eval calls need data and will fail — same contract as the
        reference's freed booster."""
        drv = self._driver
        drv._materialize()
        # snapshot the model-header fields that are derived from the
        # training data at save time (the oracle rejects a model file
        # without feature_infos)
        drv.loaded_params["feature_infos"] = drv._feature_infos()
        # keep the bin mappers + per-feature metadata: device='tpu'
        # predict stays available on the freed (predict-only) booster
        drv.snapshot_predict_context()
        self._train_set = None
        drv.train_data = None
        drv.learner = None
        drv.train_scores = None
        drv.valid_sets = []
        drv.valid_scores = []
        drv._train_step = None
        return self

    def shuffle_models(self, start_iteration: int = 0, end_iteration: int = -1):
        self._driver.shuffle_models(start_iteration, end_iteration)
        return self
