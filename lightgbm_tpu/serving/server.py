"""Serving front end: thread-safe `ServingSession` + HTTP/JSON endpoint.

`ServingSession` is the process-local API: it owns one registry, one
micro-batcher and one stats sink, and `session.predict(name, X)` is safe
to call from any number of threads — requests coalesce in the batcher
and run serialized on its worker.  The HTTP layer is a thin stdlib
(`http.server`) translation of the same calls for non-Python clients;
`python -m lightgbm_tpu serve` binds it.  `GET /metrics` exposes the
process-global telemetry registry plus this session's serving metrics
as Prometheus text — its latency histogram and the `/stats`
percentiles derive from the same buckets.

Error contract (mirrored into HTTP statuses):
* unknown model                -> KeyError            -> 404
* malformed request            -> ValueError          -> 400
* queue at capacity (shed)     -> ServingQueueFull    -> 503
* per-request timeout          -> ServingTimeout      -> 504
* device failure               -> served via the native-walker fallback
                                  (counted in stats, never an error)
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..config import Config
from .batcher import MicroBatcher, ServingQueueFull, ServingTimeout
from .registry import ModelRegistry
from .stats import ServingStats


class ServingSession:
    """Long-lived inference service over a model registry."""

    def __init__(self, params: Optional[Dict] = None, start: bool = True):
        cfg = params if isinstance(params, Config) else Config(dict(params or {}))
        self.config = cfg
        from .. import obs

        obs.configure_from_config(cfg)  # tpu_telemetry / tpu_trace_dir
        self._stats = ServingStats(window=int(cfg.serving_stats_window))
        self.registry = ModelRegistry(cfg, self._stats)
        self.batcher = MicroBatcher(
            max_batch_rows=int(cfg.serving_max_batch_rows),
            max_wait_ms=float(cfg.serving_max_wait_ms),
            queue_rows=int(cfg.serving_queue_rows),
            stats=self._stats)
        if start:
            self.batcher.start()

    # ------------------------------------------------------------------
    def load(self, name: str, **kwargs) -> str:
        """Load/hot-swap a model (see ModelRegistry.load); returns the
        `name@version` key."""
        return self.registry.load(name, **kwargs).key

    def unload(self, name: str) -> None:
        self.registry.unload(name)

    def models(self):
        return self.registry.models()

    def stats(self) -> Dict:
        return self._stats.snapshot()

    def metrics_text(self) -> str:
        """Prometheus exposition text: the process-global registry
        (train/collective/checkpoint/phase metrics) plus this session's
        serving metrics.  The serving latency histogram here and the
        `/stats` percentiles derive from the SAME buckets."""
        from ..obs import REGISTRY

        return REGISTRY.to_prometheus_text() + self._stats.to_prometheus_text()

    # ------------------------------------------------------------------
    def predict(self, name: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                timeout_ms: Optional[float] = None) -> np.ndarray:
        """Micro-batched predict: blocks until this request's rows come
        back (or sheds/times out).  Results are exactly what
        `entry.booster.predict` returns for the same rows — coalescing
        never changes a row's value (the traversal is row-independent)."""
        entry = self.registry.resolve(name)
        from ..basic import _to_2d_array

        Xm = _to_2d_array(X, entry.booster.pandas_categorical)
        Xm = np.ascontiguousarray(np.atleast_2d(Xm), np.float64)
        if Xm.shape[0] > self.batcher.queue_rows:
            # no load level can ever admit this: a 503 would invite
            # pointless retries, so fail it as a caller error (HTTP 400)
            raise ValueError(
                f"request of {Xm.shape[0]} rows exceeds serving_queue_rows="
                f"{self.batcher.queue_rows}; raise the limit or split the "
                "request")
        # None matches Booster.predict's default (best_iteration when
        # set) — the same value warmup pre-compiled
        ni = (entry.default_num_iteration() if num_iteration is None
              else int(num_iteration))
        # feature width is part of the batch key: a wrong-width request
        # must fail alone, never poison the batch it would coalesce into
        key = (entry.key, bool(raw_score), ni, Xm.shape[1])
        runner = lambda Xb: entry.predict(Xb, raw_score=raw_score,  # noqa: E731
                                          num_iteration=ni)
        timeout_s = (float(self.config.serving_timeout_ms)
                     if timeout_ms is None else float(timeout_ms)) / 1e3
        # oversize requests split into max_batch_rows slices so every
        # launch stays inside the warmed row buckets (an unsplit 10k-row
        # batch would hit a cold 16k-bucket compile); admission is
        # all-or-nothing and ONE timeout budget covers all slices
        max_rows = self.batcher.max_batch_rows
        reqs = self.batcher.submit_many(
            key, runner, [Xm[lo:lo + max_rows]
                          for lo in range(0, max(Xm.shape[0], 1), max_rows)])
        deadline = time.monotonic() + timeout_s
        try:
            outs = [self.batcher.wait(r,
                                      max(deadline - time.monotonic(), 0.0))
                    for r in reqs]
        except BaseException:
            # one slice failed/timed out: the whole logical request is
            # dead — shed its remaining queued slices
            for r in reqs:
                r.abandoned = True
            raise
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def close(self) -> None:
        self.batcher.close()


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------
class _ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    session: ServingSession = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-serve"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # no stderr chatter per request
        pass

    def _json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> Dict:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            obj = json.loads(raw.decode() or "{}")
        except json.JSONDecodeError as exc:
            raise ValueError(f"malformed JSON body: {exc}") from exc
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # ------------------------------------------------------------------
    def _text(self, code: int, text: str,
              content_type: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------------
    def do_GET(self) -> None:
        session = self.server.session
        if self.path == "/stats":
            self._json(200, session.stats())
        elif self.path == "/metrics":
            # Prometheus text-format scrape target (version 0.0.4)
            self._text(200, session.metrics_text())
        elif self.path == "/models":
            self._json(200, {"models": session.models()})
        elif self.path == "/healthz":
            self._json(200, {"ok": True})
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        session = self.server.session
        try:
            body = self._body()
            if self.path == "/predict":
                name = body.get("model")
                rows = body.get("rows")
                if not name or rows is None:
                    raise ValueError("need 'model' and 'rows'")
                X = np.asarray(rows, np.float64)
                out = session.predict(
                    str(name), X, raw_score=bool(body.get("raw_score")),
                    num_iteration=body.get("num_iteration"),
                    timeout_ms=body.get("timeout_ms"))
                self._json(200, {"model": str(name),
                                 "predictions": np.asarray(out).tolist()})
            elif self.path == "/load":
                name = body.get("name")
                if not name:
                    raise ValueError("need 'name'")
                key = session.load(
                    str(name), model_file=body.get("model_file"),
                    model_str=body.get("model_str"),
                    params=body.get("params"),
                    version=body.get("version"))
                self._json(200, {"loaded": key})
            else:
                self._json(404, {"error": f"no route {self.path}"})
        except ServingQueueFull as exc:
            self._json(503, {"error": str(exc)})
        except ServingTimeout as exc:
            self._json(504, {"error": str(exc)})
        except KeyError as exc:
            self._json(404, {"error": str(exc.args[0]) if exc.args
                             else str(exc)})
        except ValueError as exc:
            self._json(400, {"error": str(exc)})
        except Exception as exc:
            from ..utils.log import LightGBMError

            if isinstance(exc, LightGBMError):
                # data errors (feature-count mismatch, ...) are the
                # CALLER's fault, not a server fault
                self._json(400, {"error": str(exc)})
            else:  # pragma: no cover - defensive
                self._json(500, {"error": f"{type(exc).__name__}: {exc}"})


def serve_http(session: ServingSession, host: str = "127.0.0.1",
               port: int = 18080) -> _ServingHTTPServer:
    """Start the HTTP endpoint on a daemon thread; returns the server
    (its bound port is `server.server_address[1]`; stop with
    `server.shutdown()`)."""
    server = _ServingHTTPServer((host, int(port)), _Handler)
    server.session = session
    thread = threading.Thread(target=server.serve_forever,
                              name="lgbm-serving-http", daemon=True)
    thread.start()
    return server


def serve_forever(session: ServingSession, host: str = "127.0.0.1",
                  port: int = 18080) -> None:
    """Blocking variant for the CLI `serve` task."""
    server = _ServingHTTPServer((host, int(port)), _Handler)
    server.session = session
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # clean ^C exit for the CLI
        pass
    finally:
        server.server_close()
        session.close()
