"""Serving front end: thread-safe `ServingSession` + HTTP/JSON endpoint.

`ServingSession` is the process-local API: it owns one registry, one
micro-batcher, one admission controller and one stats sink, and
`session.predict(name, X)` is safe to call from any number of threads —
requests coalesce in the batcher and run serialized on its worker.  The
HTTP layer is a thin stdlib (`http.server`) translation of the same
calls for non-Python clients; `python -m lightgbm_tpu serve` binds it.
`GET /metrics` exposes the process-global telemetry registry plus this
session's serving metrics as Prometheus text — its latency histogram
and the `/stats` percentiles derive from the same buckets.

Request metadata propagates from HTTP into the batcher:

* `X-Deadline-Ms` header (or `deadline_ms` body field) — the caller's
  end-to-end budget; requests still queued past it are cancelled before
  burning device time (`ServingExpired`, counted `requests_expired`).
* `X-Priority` header (or `priority` body field) — `high` | `normal` |
  `low`; under pressure the admission controller sheds low first.

Error contract (mirrored into HTTP statuses; every shed/timeout body is
structured JSON `{"error", "code", "retry_after_ms"?}` and 429/503
responses carry a `Retry-After` header):

| condition                                | exception          | HTTP |
|------------------------------------------|--------------------|------|
| unknown model                            | KeyError           | 404  |
| malformed request                        | ValueError         | 400  |
| data error (feature count, dtype...)     | LightGBMError      | 400  |
| adaptive admission shed (priority class) | ServingOverloaded  | 429  |
| hard queue capacity (serving_queue_rows) | ServingQueueFull   | 503  |
| session draining                         | ServingDraining    | 503  |
| caller wait budget exhausted             | ServingTimeout     | 504  |
| expired in queue (X-Deadline-Ms)         | ServingExpired     | 504  |
| load over the serving HBM budget         | ServingMemoryExhausted | 507 |
| device failure                           | served via failover/breaker (counted, never an error) | — |
| dispatch OOM                             | served via walker failover + cold-model eviction (counted, never an error) | — |

Drain lifecycle: `POST /drain` (or SIGTERM under `python -m
lightgbm_tpu serve`) stops admission — new requests get 503 +
`Retry-After` — flushes every in-flight batch, and reports
`{"drained": true}` when the queue is empty.  Zero requests are lost
or double-answered: every admitted request resolves exactly once, by
result or by structured error.  Hot-swap (`POST /load` on a live name)
needs no drain: in-flight requests finish against their resolved entry
while new ones see the new version.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..config import Config
from ..utils import membudget
from .admission import (AdmissionController, ServingDraining,
                        ServingOverloaded, resolve_priority)
from .batcher import (MicroBatcher, ServingExpired, ServingQueueFull,
                      ServingTimeout)
from .registry import ModelRegistry
from .stats import ServingStats


class ServingSession:
    """Long-lived inference service over a model registry."""

    def __init__(self, params: Optional[Dict] = None, start: bool = True):
        cfg = params if isinstance(params, Config) else Config(dict(params or {}))
        self.config = cfg
        from .. import obs

        obs.configure_from_config(cfg)  # tpu_telemetry / tpu_trace_dir
        self._stats = ServingStats(window=int(cfg.serving_stats_window))
        self.registry = ModelRegistry(cfg, self._stats)
        # fleet dispatch (ISSUE 19): one batcher worker per serving
        # device; the AIMD step scales with dispatch lanes (capacity
        # re-probes proportionally to the fleet, not to one device)
        devices = len(self.registry.devices)
        self.admission = AdmissionController(
            self._stats, slo_ms=float(cfg.serving_slo_ms),
            queue_rows=int(cfg.serving_queue_rows),
            max_batch_rows=int(cfg.serving_max_batch_rows),
            interval_ms=float(cfg.serving_aimd_interval_ms),
            step_rows=int(cfg.serving_aimd_step_rows),
            backoff=float(cfg.serving_aimd_backoff),
            min_wait_ms=float(cfg.serving_min_wait_ms),
            max_wait_ms=float(cfg.serving_max_wait_ms),
            retry_after_ms=float(cfg.serving_retry_after_ms),
            enabled=bool(cfg.serving_admission),
            devices=devices)
        self.batcher = MicroBatcher(
            max_batch_rows=int(cfg.serving_max_batch_rows),
            max_wait_ms=float(cfg.serving_max_wait_ms),
            queue_rows=int(cfg.serving_queue_rows),
            stats=self._stats,
            window_fn=self.admission.batch_window_s,
            dispatch_timeout_ms=float(cfg.serving_dispatch_timeout_ms),
            devices=devices)
        self._drain_lock = threading.Lock()
        self._drained = False
        if start:
            self.batcher.start()

    # ------------------------------------------------------------------
    def load(self, name: str, **kwargs) -> str:
        """Load/hot-swap a model (see ModelRegistry.load); returns the
        `name@version` key."""
        return self.registry.load(name, **kwargs).key

    def unload(self, name: str) -> None:
        self.registry.unload(name)

    def models(self):
        return self.registry.models()

    def stats(self) -> Dict:
        out = self._stats.snapshot()
        out.update(self.admission.snapshot())
        # process-runtime gauges (ISSUE 12): RSS / uptime / threads /
        # fds / GC — scrape-time reads, same values /metrics exports
        from ..obs import resources

        out.update(resources.process_runtime_stats())
        out.update(self.memory_pressure())
        return out

    def memory_pressure(self) -> Dict:
        """Serving HBM budget/pressure snapshot (ISSUE 15): explicit
        None where no budget resolves — `/stats` and `/healthz` both
        carry it, and `lgbm_serving_hbm_pressure` is the gauge twin."""
        budget = membudget.serving_budget_bytes(self.config)
        resident = sum(int(e.hbm_bytes)
                       for e in self.registry.entries())
        return {
            "hbm_budget_bytes": budget,
            "hbm_models_bytes": resident,
            "hbm_pressure": (round(resident / budget, 4)
                             if budget else None),
        }

    def blackbox(self) -> Dict:
        """The live flight-recorder ring (GET /debug/blackbox): what
        this process was recently doing, without waiting for it to die
        and dump."""
        from ..obs import flightrecorder
        from ..utils import faultline

        return {"host": faultline.host_index(),
                "ring_depth": flightrecorder.depth(),
                "last_dump": flightrecorder.last_dump(),
                "entries": flightrecorder.entries()}

    def drift(self) -> Dict:
        """Model/data drift snapshot (ISSUE 14): per resident model with
        a drift monitor, the per-feature PSI/JS table, NaN and unseen-
        category rates, and the raw-score-histogram divergence against
        its training profile.  The scrape ABSORBS pending samples (the
        dispatch path only stashes them), so this is also what
        refreshes the `lgbm_drift_*` gauges — `GET /drift` and
        `GET /metrics` derive from the same accumulators and cannot
        disagree."""
        models = {}
        for entry in self.registry.entries():
            if entry.drift is not None:
                models[entry.key] = entry.drift.snapshot()
        return {"models": models,
                "psi_warn": float(self.config.serving_drift_psi_warn),
                "sample_rows": int(self.config.serving_drift_sample_rows)}

    def metrics_text(self) -> str:
        """Prometheus exposition text: the process-global registry
        (train/collective/checkpoint/phase metrics) plus this session's
        serving metrics.  The serving latency histogram here and the
        `/stats` percentiles derive from the SAME buckets; the
        process-runtime and drift gauges are refreshed per scrape."""
        from ..obs import REGISTRY, resources

        resources.publish_process_gauges(REGISTRY)
        self.drift()  # refresh lgbm_drift_* gauges from the accumulators
        return REGISTRY.to_prometheus_text() + self._stats.to_prometheus_text()

    # ------------------------------------------------------------------
    def predict(self, name: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                timeout_ms: Optional[float] = None,
                deadline_ms: Optional[float] = None,
                priority=None) -> np.ndarray:
        """Micro-batched predict: blocks until this request's rows come
        back (or sheds/expires/times out).  Results are exactly what
        `entry.booster.predict` returns for the same rows — coalescing
        never changes a row's value (the traversal is row-independent).

        deadline_ms: the caller's END-TO-END budget (X-Deadline-Ms);
        it caps the wait AND cancels still-queued slices at expiry.
        priority: 'high' | 'normal' | 'low' admission class."""
        prio = resolve_priority(priority)
        entry = self.registry.resolve(name)
        from ..basic import _to_2d_array

        Xm = _to_2d_array(X, entry.booster.pandas_categorical)
        Xm = np.ascontiguousarray(np.atleast_2d(Xm), np.float64)
        if Xm.shape[0] > self.batcher.queue_rows:
            # no load level can ever admit this: a 503 would invite
            # pointless retries, so fail it as a caller error (HTTP 400)
            raise ValueError(
                f"request of {Xm.shape[0]} rows exceeds serving_queue_rows="
                f"{self.batcher.queue_rows}; raise the limit or split the "
                "request")
        # adaptive admission gate (429/503 shed) BEFORE any queue state
        # mutates: an overloaded shed costs one histogram read, zero
        # device work and zero queue churn
        self.admission.admit(int(Xm.shape[0]), prio,
                             self.batcher.stats.snapshot_queue_depth())
        # None matches Booster.predict's default (best_iteration when
        # set) — the same value warmup pre-compiled
        ni = (entry.default_num_iteration() if num_iteration is None
              else int(num_iteration))
        # feature width is part of the batch key: a wrong-width request
        # must fail alone, never poison the batch it would coalesce into
        key = (entry.key, bool(raw_score), ni, Xm.shape[1])
        # replicated entries take per-device routing: the batcher tells
        # the runner which worker/device the batch landed on and filters
        # candidates through the entry's non-consuming breaker peek
        per_device = len(entry.replicas) > 1
        runner = lambda Xb, device=None: entry.predict(  # noqa: E731
            Xb, raw_score=raw_score, num_iteration=ni,
            device_index=device)
        timeout_s = (float(self.config.serving_timeout_ms)
                     if timeout_ms is None else float(timeout_ms)) / 1e3
        if deadline_ms is not None:
            # the deadline caps the whole wait: a 10 s default timeout
            # must not outlive a 50 ms caller budget
            timeout_s = min(timeout_s, max(float(deadline_ms), 0.0) / 1e3)
        abs_deadline = (time.monotonic() + max(float(deadline_ms), 0.0) / 1e3
                        if deadline_ms is not None else None)
        # oversize requests split into max_batch_rows slices so every
        # launch stays inside the warmed row buckets (an unsplit 10k-row
        # batch would hit a cold 16k-bucket compile); admission is
        # all-or-nothing and ONE timeout budget covers all slices
        max_rows = self.batcher.max_batch_rows
        try:
            reqs = self.batcher.submit_many(
                key, runner,
                [Xm[lo:lo + max_rows]
                 for lo in range(0, max(Xm.shape[0], 1), max_rows)],
                deadline=abs_deadline,
                fallback=entry.native_runner(bool(raw_score), ni),
                on_error=entry.record_dispatch_error,
                per_device=per_device, device_ok=entry.replica_ok)
        except RuntimeError as exc:
            if self.batcher.draining:
                raise ServingDraining(
                    "serving session is draining; admission closed",
                    self.admission.retry_after_s) from exc
            raise
        deadline = time.monotonic() + timeout_s
        try:
            outs = [self.batcher.wait(r,
                                      max(deadline - time.monotonic(), 0.0))
                    for r in reqs]
        except BaseException:
            # one slice failed/timed out: the whole logical request is
            # dead — shed its remaining queued slices
            for r in reqs:
                r.abandoned = True
            raise
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    # ------------------------------------------------------------------
    def drain(self, timeout_s: Optional[float] = None) -> Dict:
        """Drain lifecycle: stop admission, flush in-flight batches,
        hand off cleanly.  Idempotent; returns the outcome dict the
        `POST /drain` route serializes.  Zero admitted requests are
        lost or double-answered: each resolves exactly once before the
        flush reports complete."""
        from .. import obs

        if timeout_s is None:
            timeout_s = float(self.config.serving_drain_timeout_ms) / 1e3
        with self._drain_lock:
            first = not self._drained
            self.admission.begin_drain()   # new requests -> 503
            with obs.span("serve/drain"):
                flushed = self.batcher.drain(timeout_s)
            if first and flushed:
                self._stats.count("drains")
                self._drained = True
        return {"drained": bool(flushed),
                "queued_rows": self._stats.snapshot()["queue_depth_rows"]}

    def close(self) -> None:
        """Shutdown rides the drain path: flush, then stop the worker."""
        self.admission.begin_drain()
        self.batcher.close()


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------
class _ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    session: ServingSession = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-serve"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # no stderr chatter per request
        pass

    def _json(self, code: int, obj, retry_after_s: float = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after_s is not None:
            # whole seconds per RFC 9110 (minimum 1: a 0 invites an
            # immediate hammer-retry)
            self.send_header("Retry-After",
                             str(max(int(round(retry_after_s)), 1)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, exc: BaseException, error_code: str,
               retry_after_s: float = None) -> None:
        """Structured JSON error body; sheds carry machine-readable
        `code` + `retry_after_ms` so clients can back off correctly."""
        obj = {"error": str(exc), "code": error_code}
        if retry_after_s is not None:
            obj["retry_after_ms"] = int(retry_after_s * 1e3)
        self._json(code, obj, retry_after_s=retry_after_s)

    def _body(self) -> Dict:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            obj = json.loads(raw.decode() or "{}")
        except json.JSONDecodeError as exc:
            raise ValueError(f"malformed JSON body: {exc}") from exc
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # ------------------------------------------------------------------
    def _text(self, code: int, text: str,
              content_type: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------------
    def do_GET(self) -> None:
        session = self.server.session
        if self.path == "/stats":
            self._json(200, session.stats())
        elif self.path == "/metrics":
            # Prometheus text-format scrape target (version 0.0.4)
            self._text(200, session.metrics_text())
        elif self.path == "/models":
            self._json(200, {"models": session.models()})
        elif self.path == "/drift":
            # model/data health: PSI/JS drift vs the training profiles
            self._json(200, session.drift())
        elif self.path == "/debug/blackbox":
            # the live flight-recorder ring: the postmortem view
            # WITHOUT the mortem
            self._json(200, session.blackbox())
        elif self.path == "/healthz":
            if session.admission.draining:
                # draining replicas must fall out of load-balancer
                # rotation before the flush finishes
                self._json(503, {"ok": False, "draining": True})
            else:
                # budget/pressure ride the health probe: a fleet
                # scheduler can route new model loads away from a
                # replica already near its HBM budget
                self._json(200, {"ok": True,
                                 **session.memory_pressure()})
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        session = self.server.session
        try:
            body = self._body()
            if self.path == "/predict":
                name = body.get("model")
                rows = body.get("rows")
                if not name or rows is None:
                    raise ValueError("need 'model' and 'rows'")
                X = np.asarray(rows, np.float64)
                deadline_ms = self.headers.get("X-Deadline-Ms",
                                               body.get("deadline_ms"))
                priority = self.headers.get("X-Priority",
                                            body.get("priority"))
                out = session.predict(
                    str(name), X, raw_score=bool(body.get("raw_score")),
                    num_iteration=body.get("num_iteration"),
                    timeout_ms=body.get("timeout_ms"),
                    deadline_ms=(float(deadline_ms)
                                 if deadline_ms is not None else None),
                    priority=priority)
                self._json(200, {"model": str(name),
                                 "predictions": np.asarray(out).tolist()})
            elif self.path == "/load":
                name = body.get("name")
                if not name:
                    raise ValueError("need 'name'")
                key = session.load(
                    str(name), model_file=body.get("model_file"),
                    model_str=body.get("model_str"),
                    params=body.get("params"),
                    version=body.get("version"))
                self._json(200, {"loaded": key})
            elif self.path == "/drain":
                timeout_s = body.get("timeout_s")
                if timeout_s is not None:
                    # validate BEFORE any side effect: begin_drain() is
                    # irreversible, so a malformed body must 400 here,
                    # not TypeError mid-drain with admission closed
                    try:
                        timeout_s = float(timeout_s)
                    except (TypeError, ValueError):
                        raise ValueError(
                            f"timeout_s must be a number, got "
                            f"{timeout_s!r}") from None
                self._json(200, session.drain(timeout_s=timeout_s))
            else:
                self._json(404, {"error": f"no route {self.path}"})
        except ServingOverloaded as exc:
            self._error(429, exc, "overload",
                        retry_after_s=exc.retry_after_s)
        except ServingDraining as exc:
            self._error(503, exc, "draining",
                        retry_after_s=exc.retry_after_s)
        except ServingQueueFull as exc:
            self._error(503, exc, "capacity",
                        retry_after_s=session.admission.retry_after_s)
        except ServingExpired as exc:
            self._error(504, exc, "deadline")
        except ServingTimeout as exc:
            self._error(504, exc, "timeout")
        except membudget.ServingMemoryExhausted as exc:
            # 507 Insufficient Storage: the load's predicted bytes do
            # not fit the serving HBM budget (itemized plan in body)
            self._error(507, exc, "memory")
        except KeyError as exc:
            self._json(404, {"error": str(exc.args[0]) if exc.args
                             else str(exc)})
        except ValueError as exc:
            self._json(400, {"error": str(exc)})
        except Exception as exc:
            from ..utils.log import LightGBMError

            if isinstance(exc, LightGBMError):
                # data errors (feature-count mismatch, ...) are the
                # CALLER's fault, not a server fault
                self._json(400, {"error": str(exc)})
            elif membudget.is_oom_error(exc):
                # a classified device OOM that escaped the failover
                # layers is still a memory verdict, not an anonymous
                # 500 — keep the 507 contract
                self._error(507, exc, "memory")
            else:  # pragma: no cover - defensive
                self._json(500, {"error": f"{type(exc).__name__}: {exc}"})


def serve_http(session: ServingSession, host: str = "127.0.0.1",
               port: int = 18080) -> _ServingHTTPServer:
    """Start the HTTP endpoint on a daemon thread; returns the server
    (its bound port is `server.server_address[1]`; stop with
    `server.shutdown()`)."""
    server = _ServingHTTPServer((host, int(port)), _Handler)
    server.session = session
    thread = threading.Thread(target=server.serve_forever,
                              name="lgbm-serving-http", daemon=True)
    thread.start()
    return server


def serve_forever(session: ServingSession, host: str = "127.0.0.1",
                  port: int = 18080) -> None:
    """Blocking variant for the CLI `serve` task.  SIGTERM rides the
    drain lifecycle: admission stops, in-flight batches flush, the
    socket closes — zero accepted requests lost."""
    import signal

    server = _ServingHTTPServer((host, int(port)), _Handler)
    server.session = session

    def _term(signum, frame):  # pragma: no cover - signal timing
        # drain THEN stop accepting: requests admitted before the
        # signal flush to completion; shutdown() must come from another
        # thread (serve_forever blocks this one)
        threading.Thread(target=lambda: (session.drain(),
                                         server.shutdown()),
                         daemon=True).start()

    try:
        prior = signal.signal(signal.SIGTERM, _term)
    except ValueError:  # pragma: no cover - non-main thread
        prior = None
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # clean ^C exit for the CLI
        pass
    finally:
        if prior is not None:
            signal.signal(signal.SIGTERM, prior)
        server.server_close()
        session.drain()
        session.close()
