"""Serving observability: shared-histogram latency percentiles + counters.

One `ServingStats` instance is shared by the whole serving stack
(registry, batcher, session, HTTP endpoint).  Everything is O(1) per
event: counters and the latency/queue-wait/dispatch histograms live in a
PRIVATE `obs.MetricsRegistry` (per-session, so concurrent sessions never
cross-count), and the `/stats` percentiles are computed from the SAME
fixed-bucket latency histogram the `GET /metrics` Prometheus endpoint
exports — the two surfaces derive from one estimator
(`obs.metrics.histogram_quantile`) and cannot disagree.  The
compile-cache accounting is a set of launch-shape keys — a shape first
seen AFTER warmup is a `compile_cache_misses` increment, which is
exactly the quantity the warmup contract promises stays at zero for
request sizes within `serving_max_batch_rows`.
"""

from __future__ import annotations

import time
from typing import Dict, Hashable, Tuple

from ..obs.metrics import MetricsRegistry
from ..utils import lockcheck

_COUNTERS = (
    "requests_total", "rows_total", "batches_total", "requests_shed",
    "requests_timeout", "device_fallbacks", "compile_cache_hits",
    "compile_cache_misses", "compiles_warmup", "models_loaded",
    "models_evicted", "breaker_open", "breaker_halfopen_probes",
    # adaptive admission / deadline / drain / failover (ISSUE 11):
    # requests_overload       = AIMD priority-class sheds (HTTP 429)
    # requests_expired        = cancelled in queue past their deadline
    #                           (separate from requests_timeout, the
    #                           dispatch-WAIT expiries)
    # requests_drain_rejected = refused because the session is draining
    # dispatch_timeouts       = runner hangs past
    #                           serving_dispatch_timeout_ms
    # dispatch_failovers      = batches re-run on the fallback runner
    #                           after a device-path raise/hang
    # drains                  = drain lifecycles completed
    "requests_overload", "requests_expired", "requests_drain_rejected",
    "dispatch_timeouts", "dispatch_failovers", "drains",
    # model/data health (ISSUE 14): drift_warnings = PSI warn-threshold
    # crossings recorded by the per-model DriftMonitor
    "drift_warnings",
    # memory pressure (ISSUE 15):
    # dispatch_oom        = classified device OOMs on the dispatch path
    #                       (served via walker failover, breaker fed)
    # models_refused_hbm  = loads refused by the serving HBM budget
    #                       (the HTTP 507 surface)
    # evictions_pressure  = cold models evicted by byte pressure or an
    #                       OOM-triggered relieve (subset of
    #                       models_evicted)
    "dispatch_oom", "models_refused_hbm", "evictions_pressure",
    # fleet-scale serving (ISSUE 19):
    # replica_failovers = batches re-dispatched to a sibling replica
    #                     after one device's attempt raised (distinct
    #                     from dispatch_failovers, the native-walker
    #                     escape once EVERY replica refused)
    # aot_cache_hits    = per-(device, bucket) executables deserialized
    #                     from the AOT cache at model load
    # aot_cache_misses  = buckets that fell back to a warm compile
    #                     (absent, corrupt, or stale .aotx)
    "replica_failovers", "aot_cache_hits", "aot_cache_misses",
)

# serving latency buckets: sub-ms device hits through multi-second
# timeout territory
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2,
    0.5, 1.0, 2.0, 5.0, 10.0, 30.0)

_LAT = "lgbm_serving_latency_seconds"
_QWAIT = "lgbm_serving_queue_wait_seconds"
_DISPATCH = "lgbm_serving_dispatch_seconds"


def _prom_name(counter: str) -> str:
    base = f"lgbm_serving_{counter}"
    return base if base.endswith("_total") else base + "_total"


class CircuitBreaker:
    """Failure threshold -> open -> timed half-open probe -> closed.

    Guards one model entry's DEVICE predict path: `serving_breaker_failures`
    consecutive device failures open the breaker (requests short-circuit
    to the native walker with zero device attempts); after
    `serving_breaker_cooldown_ms` ONE half-open probe retries the device
    path — success closes the breaker, failure re-opens it for another
    cooldown.  This replaces the old per-request fallback's two failure
    modes: hammering a dead device on every request, and (the sticky
    variant) never retrying a recovered one.  Transitions count into the
    shared ServingStats (`breaker_open`, `breaker_halfopen_probes`)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 stats: "ServingStats" = None):
        self._lock = lockcheck.make_lock("serving.breaker")
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.stats = stats
        self.state = "closed"
        self._failures = 0
        self._entered_at = 0.0  # when the current open/half_open began
        # failure generation: bumps on every record_failure so a
        # STRAGGLER success — a dispatch the watchdog already abandoned
        # (and recorded as failed) completing minutes later — cannot
        # wipe the failures recorded since it began (see generation /
        # record_success(gen))
        self._gen = 0

    @property
    def generation(self) -> int:
        """Snapshot before a device attempt; pass back to
        record_success so stale completions can be ignored."""
        return self._gen

    def allow(self) -> bool:
        """May this request try the device path?"""
        with self._lock:
            if self.state == "closed":
                return True
            # open -> half_open probe after the cooldown; a half_open
            # whose probe never reported back (a data error can raise
            # through BOTH paths before record_failure runs) re-probes
            # after another cooldown instead of wedging the device path
            # off forever
            if time.monotonic() - self._entered_at >= self.cooldown_s:
                self.state = "half_open"
                self._entered_at = time.monotonic()
                if self.stats is not None:
                    self.stats.count("breaker_halfopen_probes")
                from ..obs import flightrecorder

                flightrecorder.note("breaker", "half_open")
                return True
            return False

    @property
    def routable(self) -> bool:
        """Non-consuming routability peek for the fleet router.

        `allow()` CONSUMES the half-open probe slot (it transitions
        open -> half_open), so a router that merely FILTERS candidate
        replicas must not call it — two peeks would grant two probes.
        This answers "could a request be sent here right now" without
        touching state: closed/half_open, or open with the cooldown
        elapsed (the dispatch path's own allow() will then take the
        probe slot exactly once)."""
        with self._lock:
            if self.state != "open":
                return True
            return time.monotonic() - self._entered_at >= self.cooldown_s

    def record_success(self, gen: int = None) -> None:
        with self._lock:
            if gen is not None and gen != self._gen:
                # the attempt predates failures recorded while it ran
                # (watchdog-abandoned straggler): its success is stale
                # evidence and must not close/reset the breaker
                return
            if gen is None and self.state == "open":
                # an OPEN breaker closes only through an allowed
                # half-open probe (which carries a fresh generation),
                # never through an unattributed late success
                return
            if self.state != "closed":
                from ..obs import flightrecorder

                flightrecorder.note("breaker", "closed")
            self.state = "closed"
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._gen += 1
            self._failures += 1
            if self.state == "half_open" or self._failures >= self.threshold:
                if self.state != "open":
                    if self.stats is not None:
                        self.stats.count("breaker_open")
                    from ..obs import flightrecorder

                    flightrecorder.note("breaker", "open",
                                        failures=self.threshold)
                self.state = "open"
                self._entered_at = time.monotonic()
                self._failures = 0


class ServingStats:
    """Thread-safe serving counters + bucketed latency distributions.

    `window` is retained for API compatibility (it used to size a raw
    ring buffer); percentiles now come from the fixed-bucket histogram
    so the `/stats` numbers and the Prometheus `/metrics` export agree
    by construction."""

    def __init__(self, window: int = 4096):
        self._lock = lockcheck.make_lock("serving.stats")
        self.registry = MetricsRegistry()
        for key in _COUNTERS:  # pre-register so /metrics shows zeros
            self.registry.inc(_prom_name(key), 0)
        self._fill_rows = 0      # real rows dispatched
        self._fill_bucket = 0    # padded launch rows they rode in
        self._queue_depth = 0
        self._shapes: set = set()
        # drift gauge series published per model (label tuples), so an
        # unloaded/evicted model's series can be removed exactly.
        # _drift_closed holds keys whose series were cleared: an
        # in-flight scrape that snapshotted the entry BEFORE its unload
        # must not re-publish (phantom-series race); reload of the same
        # key re-opens it.  Bounded by version churn (small strings)
        self._drift_series: set = set()
        self._drift_closed: set = set()

    # -- events --------------------------------------------------------
    def count(self, key: str, n: int = 1) -> None:
        self.registry.inc(_prom_name(key), n)

    def record_latency(self, seconds: float) -> None:
        self.registry.observe(_LAT, seconds, buckets=LATENCY_BUCKETS_S,
                              help="end-to-end request latency "
                                   "(submit -> result)")

    def record_queue_wait(self, seconds: float) -> None:
        """Submit -> dispatch-start wall of one request."""
        self.registry.observe(_QWAIT, seconds, buckets=LATENCY_BUCKETS_S,
                              help="batcher queue wait "
                                   "(submit -> dispatch start)")

    def record_dispatch(self, seconds: float) -> None:
        """One coalesced batch's runner wall (the device-side cost)."""
        self.registry.observe(_DISPATCH, seconds,
                              buckets=LATENCY_BUCKETS_S,
                              help="coalesced-batch dispatch wall")

    def note_batch(self, rows: int, bucket: int, launches: int = 1) -> None:
        """One dispatched batch: `rows` real rows across `launches`
        device launches totalling `bucket` padded rows (fill ratio =
        rows / padded rows aggregated over batches)."""
        self.count("batches_total", max(int(launches), 1))
        with self._lock:
            self._fill_rows += int(rows)
            self._fill_bucket += max(int(bucket), 1)
        self.registry.inc("lgbm_serving_batch_rows_total", int(rows))
        self.registry.inc("lgbm_serving_batch_padded_rows_total",
                          max(int(bucket), 1))

    def note_shape(self, key: Hashable, warmup: bool = False,
                   compiled: bool = True) -> bool:
        """Record one jit launch shape; returns True when it is new.

        New shapes during warmup count as `compiles_warmup`; new shapes
        afterwards are `compile_cache_misses` (the number the
        zero-cold-compile acceptance test asserts on).  `compiled=False`
        registers the shape without charging either ledger — the
        AOT-deserialized executables (ISSUE 19) exist without ANY
        compile, and the ledger must say so."""
        with self._lock:
            if key in self._shapes:
                new = False
            else:
                self._shapes.add(key)
                new = True
        if not new:
            self.count("compile_cache_hits")
            return False
        if compiled:
            self.count("compiles_warmup" if warmup
                       else "compile_cache_misses")
        return True

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self._queue_depth = int(rows)
        self.registry.set_gauge("lgbm_serving_queue_depth_rows", int(rows),
                                help="rows currently queued in the "
                                     "micro-batcher")

    def set_admission(self, level_rows: float, window_s: float,
                      projection_s: float) -> None:
        """Admission-controller state published as gauges (scraped via
        /metrics beside the histograms that drive it).  The controller
        itself stays the single source of truth — `ServingSession.
        stats()` merges `AdmissionController.snapshot()`; nothing is
        mirrored here."""
        self.registry.set_gauge("lgbm_serving_admission_level_rows",
                                float(level_rows),
                                help="AIMD admitted-rows level")
        self.registry.set_gauge("lgbm_serving_batch_window_ms",
                                float(window_s) * 1e3,
                                help="adaptive batcher coalescing window")
        self.registry.set_gauge("lgbm_serving_slo_projection_ms",
                                float(projection_s) * 1e3,
                                help="projected new-request latency "
                                     "(queue-wait p99 + dispatch p95)")

    def set_model_hbm(self, key: str, nbytes: int) -> None:
        """Per-model device-table bytes gauge (load / hot-swap sets it,
        unload / LRU eviction zeroes it): the unit `serving_max_models`
        should have counted in — quantized tables (ROADMAP 2c) make
        "models" the wrong capacity unit, bytes the right one."""
        self.registry.set_gauge("lgbm_serving_model_hbm_bytes",
                                int(nbytes),
                                help="packed device-table bytes of one "
                                     "resident model",
                                model=str(key))

    # -- fleet-scale serving (ISSUE 19) --------------------------------
    def set_device_hbm(self, index: int, nbytes: int) -> None:
        """Per-DEVICE resident serving-table bytes (summed over every
        replica placed there).  Published for all devices in the
        serving set, zeros included, so eviction tests can assert a
        replicated model's bytes left EVERY device."""
        self.registry.set_gauge("lgbm_serving_device_hbm_bytes",
                                int(nbytes),
                                help="resident serving-table bytes on "
                                     "one device of the fleet",
                                device=str(int(index)))

    def note_device_dispatch(self, device: int, rows: int) -> None:
        """One coalesced batch completed on one device's worker — the
        per-device goodput ledger `tools/serve_bench.py --devices`
        breaks down."""
        self.registry.inc("lgbm_serving_device_dispatches_total", 1,
                          help="coalesced batches dispatched per device",
                          device=str(int(device)))
        self.registry.inc("lgbm_serving_device_rows_total", int(rows),
                          help="real rows served per device",
                          device=str(int(device)))

    def clear_model_hbm(self, key: str) -> None:
        """Remove a departed model's gauge series entirely (unload /
        LRU eviction): a zeroed-but-resident series per version ever
        loaded would grow /metrics without bound on a hot-swapping
        server."""
        self.registry.remove("lgbm_serving_model_hbm_bytes",
                             model=str(key))

    # -- model/data health (ISSUE 14) ----------------------------------
    # The set_gauge runs INSIDE the stats lock on purpose: a scrape
    # that snapshotted an entry just before its unload would otherwise
    # re-create the gauge after clear_drift removed it, leaving a
    # phantom per-model series forever (the lock + _drift_closed check
    # serialize publish against clear).  Lock order stats._lock ->
    # registry family lock is one-way; nothing takes them reversed.
    def _set_drift_gauge(self, series, value: float, help: str,
                         **labels) -> None:
        name, model, _feat = series
        with self._lock:
            if model in self._drift_closed:
                return  # unloaded while the scrape was in flight
            self._drift_series.add(series)
            self.registry.set_gauge(name, value, help=help, **labels)

    def set_drift_psi(self, model: str, feature: str, value: float) -> None:
        """Per-(model, feature) PSI gauge — refreshed by every drift
        snapshot (GET /drift, GET /metrics scrapes)."""
        self._set_drift_gauge(
            ("lgbm_drift_psi", str(model), str(feature)), float(value),
            help="per-feature PSI of sampled serving traffic vs the "
                 "training profile",
            model=str(model), feature=str(feature))

    def set_drift_score_js(self, model: str, value: float) -> None:
        self._set_drift_gauge(
            ("lgbm_drift_score_js", str(model), None), float(value),
            help="Jensen-Shannon divergence of the served raw-score "
                 "histogram vs the training profile (max over classes)",
            model=str(model))

    def set_drift_rows(self, model: str, rows: int) -> None:
        self._set_drift_gauge(
            ("lgbm_drift_sampled_rows", str(model), None), float(rows),
            help="rows absorbed by the drift monitor since model load",
            model=str(model))

    def set_drift_warn_active(self, model: str, active: bool) -> None:
        """1 while the model's PSI sits at/above serving_drift_psi_warn,
        0 otherwise — the pollable twin of the log-only psi_warn re-arm
        (ISSUE 17): the continual trigger and operators read state, not
        log text.  Same tombstone discipline as every drift series."""
        self._set_drift_gauge(
            ("lgbm_drift_warn_active", str(model), None),
            1.0 if active else 0.0,
            help="1 while sampled-traffic PSI is at or above "
                 "serving_drift_psi_warn (re-arms below it)",
            model=str(model))

    def reopen_drift(self, model: str) -> None:
        """Re-arm drift publishing for a (re)loaded model key — undoes
        a prior clear_drift tombstone."""
        with self._lock:
            self._drift_closed.discard(str(model))

    def clear_drift(self, model: str) -> None:
        """Drop a departed model's drift series (unload / LRU eviction)
        — same no-dead-series contract as clear_model_hbm.  Also
        tombstones the key so an in-flight scrape cannot re-publish."""
        with self._lock:
            gone = {s for s in self._drift_series if s[1] == str(model)}
            self._drift_series -= gone
            self._drift_closed.add(str(model))
        for name, mdl, feat in gone:
            if feat is None:
                self.registry.remove(name, model=mdl)
            else:
                self.registry.remove(name, model=mdl, feature=feat)

    def set_total_hbm(self, nbytes: int) -> None:
        self.registry.set_gauge("lgbm_serving_models_hbm_bytes",
                                int(nbytes),
                                help="packed device-table bytes across "
                                     "all resident models")

    def set_hbm_pressure(self, ratio: float) -> None:
        """Resident-model bytes / serving HBM budget (only published
        when a budget resolves — no fictional 0 on budget-less runs)."""
        self.registry.set_gauge("lgbm_serving_hbm_pressure",
                                float(ratio),
                                help="resident model bytes as a "
                                     "fraction of the serving HBM "
                                     "budget")

    def snapshot_queue_depth(self) -> int:
        """Cheap queue-depth read for the per-request admission gate
        (the full snapshot() walks every counter)."""
        with self._lock:
            return self._queue_depth

    # -- admission feedback --------------------------------------------
    # samples the AIMD projection reads from each ring; capped by the
    # configured obs.metrics sample ring (tpu_obs_ring_samples) — a
    # smaller ring legitimately narrows the projection window
    _RECENT = 256

    def recent_wait_profile(self):
        """(queue_wait_p99_s, dispatch_p95_s, n) over the most recent
        raw samples in the PR-10 histogram rings — the closed-loop
        signal the admission controller AIMDs against.  Uses the raw
        rings rather than the cumulative buckets so a long-gone
        overload episode cannot keep the projection pinned high."""
        qs = self.registry.histogram_samples(_QWAIT)[-self._RECENT:]
        ds = self.registry.histogram_samples(_DISPATCH)[-self._RECENT:]
        n = len(qs)
        if not qs:
            return 0.0, 0.0, 0
        qs = sorted(qs)
        q99 = qs[min(int(0.99 * (len(qs) - 1) + 0.5), len(qs) - 1)]
        if ds:
            ds = sorted(ds)
            d95 = ds[min(int(0.95 * (len(ds) - 1) + 0.5), len(ds) - 1)]
        else:
            d95 = 0.0
        return float(q99), float(d95), n

    # -- reading -------------------------------------------------------
    def snapshot(self) -> Dict:
        out = {key: int(self.registry.value(_prom_name(key)))
               for key in _COUNTERS}
        with self._lock:
            out["queue_depth_rows"] = self._queue_depth
            out["batch_fill_ratio"] = (
                round(self._fill_rows / self._fill_bucket, 4)
                if self._fill_bucket else 0.0)
        n, _ = self.registry.histogram_stats(_LAT)
        out["latency_window"] = int(n)
        for tag, q in (("latency_p50_ms", 0.50), ("latency_p95_ms", 0.95),
                       ("latency_p99_ms", 0.99)):
            out[tag] = round(
                self.registry.histogram_quantile(_LAT, q) * 1e3, 3)
        qn, qs = self.registry.histogram_stats(_QWAIT)
        out["queue_wait_mean_ms"] = round(qs / qn * 1e3, 3) if qn else 0.0
        dn, dsum = self.registry.histogram_stats(_DISPATCH)
        out["dispatch_mean_ms"] = round(dsum / dn * 1e3, 3) if dn else 0.0
        return out

    def to_prometheus_text(self) -> str:
        """This session's serving metrics as Prometheus exposition text
        (the `GET /metrics` endpoint appends it to the process-global
        registry's)."""
        return self.registry.to_prometheus_text()
