"""Serving observability: shared-histogram latency percentiles + counters.

One `ServingStats` instance is shared by the whole serving stack
(registry, batcher, session, HTTP endpoint).  Everything is O(1) per
event: counters and the latency/queue-wait/dispatch histograms live in a
PRIVATE `obs.MetricsRegistry` (per-session, so concurrent sessions never
cross-count), and the `/stats` percentiles are computed from the SAME
fixed-bucket latency histogram the `GET /metrics` Prometheus endpoint
exports — the two surfaces derive from one estimator
(`obs.metrics.histogram_quantile`) and cannot disagree.  The
compile-cache accounting is a set of launch-shape keys — a shape first
seen AFTER warmup is a `compile_cache_misses` increment, which is
exactly the quantity the warmup contract promises stays at zero for
request sizes within `serving_max_batch_rows`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable, Tuple

from ..obs.metrics import MetricsRegistry

_COUNTERS = (
    "requests_total", "rows_total", "batches_total", "requests_shed",
    "requests_timeout", "device_fallbacks", "compile_cache_hits",
    "compile_cache_misses", "compiles_warmup", "models_loaded",
    "models_evicted", "breaker_open", "breaker_halfopen_probes",
)

# serving latency buckets: sub-ms device hits through multi-second
# timeout territory
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2,
    0.5, 1.0, 2.0, 5.0, 10.0, 30.0)

_LAT = "lgbm_serving_latency_seconds"
_QWAIT = "lgbm_serving_queue_wait_seconds"
_DISPATCH = "lgbm_serving_dispatch_seconds"


def _prom_name(counter: str) -> str:
    base = f"lgbm_serving_{counter}"
    return base if base.endswith("_total") else base + "_total"


class CircuitBreaker:
    """Failure threshold -> open -> timed half-open probe -> closed.

    Guards one model entry's DEVICE predict path: `serving_breaker_failures`
    consecutive device failures open the breaker (requests short-circuit
    to the native walker with zero device attempts); after
    `serving_breaker_cooldown_ms` ONE half-open probe retries the device
    path — success closes the breaker, failure re-opens it for another
    cooldown.  This replaces the old per-request fallback's two failure
    modes: hammering a dead device on every request, and (the sticky
    variant) never retrying a recovered one.  Transitions count into the
    shared ServingStats (`breaker_open`, `breaker_halfopen_probes`)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 stats: "ServingStats" = None):
        self._lock = threading.Lock()
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.stats = stats
        self.state = "closed"
        self._failures = 0
        self._entered_at = 0.0  # when the current open/half_open began

    def allow(self) -> bool:
        """May this request try the device path?"""
        with self._lock:
            if self.state == "closed":
                return True
            # open -> half_open probe after the cooldown; a half_open
            # whose probe never reported back (a data error can raise
            # through BOTH paths before record_failure runs) re-probes
            # after another cooldown instead of wedging the device path
            # off forever
            if time.monotonic() - self._entered_at >= self.cooldown_s:
                self.state = "half_open"
                self._entered_at = time.monotonic()
                if self.stats is not None:
                    self.stats.count("breaker_halfopen_probes")
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = "closed"
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self.state == "half_open" or self._failures >= self.threshold:
                if self.state != "open" and self.stats is not None:
                    self.stats.count("breaker_open")
                self.state = "open"
                self._entered_at = time.monotonic()
                self._failures = 0


class ServingStats:
    """Thread-safe serving counters + bucketed latency distributions.

    `window` is retained for API compatibility (it used to size a raw
    ring buffer); percentiles now come from the fixed-bucket histogram
    so the `/stats` numbers and the Prometheus `/metrics` export agree
    by construction."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self.registry = MetricsRegistry()
        for key in _COUNTERS:  # pre-register so /metrics shows zeros
            self.registry.inc(_prom_name(key), 0)
        self._fill_rows = 0      # real rows dispatched
        self._fill_bucket = 0    # padded launch rows they rode in
        self._queue_depth = 0
        self._shapes: set = set()

    # -- events --------------------------------------------------------
    def count(self, key: str, n: int = 1) -> None:
        self.registry.inc(_prom_name(key), n)

    def record_latency(self, seconds: float) -> None:
        self.registry.observe(_LAT, seconds, buckets=LATENCY_BUCKETS_S,
                              help="end-to-end request latency "
                                   "(submit -> result)")

    def record_queue_wait(self, seconds: float) -> None:
        """Submit -> dispatch-start wall of one request."""
        self.registry.observe(_QWAIT, seconds, buckets=LATENCY_BUCKETS_S,
                              help="batcher queue wait "
                                   "(submit -> dispatch start)")

    def record_dispatch(self, seconds: float) -> None:
        """One coalesced batch's runner wall (the device-side cost)."""
        self.registry.observe(_DISPATCH, seconds,
                              buckets=LATENCY_BUCKETS_S,
                              help="coalesced-batch dispatch wall")

    def note_batch(self, rows: int, bucket: int, launches: int = 1) -> None:
        """One dispatched batch: `rows` real rows across `launches`
        device launches totalling `bucket` padded rows (fill ratio =
        rows / padded rows aggregated over batches)."""
        self.count("batches_total", max(int(launches), 1))
        with self._lock:
            self._fill_rows += int(rows)
            self._fill_bucket += max(int(bucket), 1)
        self.registry.inc("lgbm_serving_batch_rows_total", int(rows))
        self.registry.inc("lgbm_serving_batch_padded_rows_total",
                          max(int(bucket), 1))

    def note_shape(self, key: Hashable, warmup: bool = False) -> bool:
        """Record one jit launch shape; returns True when it is new.

        New shapes during warmup count as `compiles_warmup`; new shapes
        afterwards are `compile_cache_misses` (the number the
        zero-cold-compile acceptance test asserts on)."""
        with self._lock:
            if key in self._shapes:
                new = False
            else:
                self._shapes.add(key)
                new = True
        if not new:
            self.count("compile_cache_hits")
            return False
        self.count("compiles_warmup" if warmup else "compile_cache_misses")
        return True

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self._queue_depth = int(rows)
        self.registry.set_gauge("lgbm_serving_queue_depth_rows", int(rows),
                                help="rows currently queued in the "
                                     "micro-batcher")

    # -- reading -------------------------------------------------------
    def snapshot(self) -> Dict:
        out = {key: int(self.registry.value(_prom_name(key)))
               for key in _COUNTERS}
        with self._lock:
            out["queue_depth_rows"] = self._queue_depth
            out["batch_fill_ratio"] = (
                round(self._fill_rows / self._fill_bucket, 4)
                if self._fill_bucket else 0.0)
        n, _ = self.registry.histogram_stats(_LAT)
        out["latency_window"] = int(n)
        for tag, q in (("latency_p50_ms", 0.50), ("latency_p95_ms", 0.95),
                       ("latency_p99_ms", 0.99)):
            out[tag] = round(
                self.registry.histogram_quantile(_LAT, q) * 1e3, 3)
        qn, qs = self.registry.histogram_stats(_QWAIT)
        out["queue_wait_mean_ms"] = round(qs / qn * 1e3, 3) if qn else 0.0
        dn, dsum = self.registry.histogram_stats(_DISPATCH)
        out["dispatch_mean_ms"] = round(dsum / dn * 1e3, 3) if dn else 0.0
        return out

    def to_prometheus_text(self) -> str:
        """This session's serving metrics as Prometheus exposition text
        (the `GET /metrics` endpoint appends it to the process-global
        registry's)."""
        return self.registry.to_prometheus_text()
