"""Serving observability: rolling latency percentiles + counters.

One `ServingStats` instance is shared by the whole serving stack
(registry, batcher, session, HTTP endpoint).  Everything is O(1) per
event under one lock: latencies land in a fixed ring buffer (percentiles
are computed lazily at `snapshot()` time), batch fill is a running
numerator/denominator, and the compile-cache accounting is a set of
launch-shape keys — a shape first seen AFTER warmup is a
`compile_cache_misses` increment, which is exactly the quantity the
warmup contract promises stays at zero for request sizes within
`serving_max_batch_rows`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable

import numpy as np

_COUNTERS = (
    "requests_total", "rows_total", "batches_total", "requests_shed",
    "requests_timeout", "device_fallbacks", "compile_cache_hits",
    "compile_cache_misses", "compiles_warmup", "models_loaded",
    "models_evicted", "breaker_open", "breaker_halfopen_probes",
)


class CircuitBreaker:
    """Failure threshold -> open -> timed half-open probe -> closed.

    Guards one model entry's DEVICE predict path: `serving_breaker_failures`
    consecutive device failures open the breaker (requests short-circuit
    to the native walker with zero device attempts); after
    `serving_breaker_cooldown_ms` ONE half-open probe retries the device
    path — success closes the breaker, failure re-opens it for another
    cooldown.  This replaces the old per-request fallback's two failure
    modes: hammering a dead device on every request, and (the sticky
    variant) never retrying a recovered one.  Transitions count into the
    shared ServingStats (`breaker_open`, `breaker_halfopen_probes`)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 stats: "ServingStats" = None):
        self._lock = threading.Lock()
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.stats = stats
        self.state = "closed"
        self._failures = 0
        self._entered_at = 0.0  # when the current open/half_open began

    def allow(self) -> bool:
        """May this request try the device path?"""
        with self._lock:
            if self.state == "closed":
                return True
            # open -> half_open probe after the cooldown; a half_open
            # whose probe never reported back (a data error can raise
            # through BOTH paths before record_failure runs) re-probes
            # after another cooldown instead of wedging the device path
            # off forever
            if time.monotonic() - self._entered_at >= self.cooldown_s:
                self.state = "half_open"
                self._entered_at = time.monotonic()
                if self.stats is not None:
                    self.stats.count("breaker_halfopen_probes")
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = "closed"
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self.state == "half_open" or self._failures >= self.threshold:
                if self.state != "open" and self.stats is not None:
                    self.stats.count("breaker_open")
                self.state = "open"
                self._entered_at = time.monotonic()
                self._failures = 0


class ServingStats:
    """Thread-safe serving counters + rolling latency window."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = max(int(window), 16)
        self._lat = np.zeros(self._window, np.float64)
        self._lat_n = 0  # total latencies ever recorded
        self._counters: Dict[str, int] = {k: 0 for k in _COUNTERS}
        self._fill_rows = 0      # real rows dispatched
        self._fill_bucket = 0    # padded launch rows they rode in
        self._queue_depth = 0
        self._shapes: set = set()

    # -- events --------------------------------------------------------
    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat[self._lat_n % self._window] = seconds
            self._lat_n += 1

    def note_batch(self, rows: int, bucket: int, launches: int = 1) -> None:
        """One dispatched batch: `rows` real rows across `launches`
        device launches totalling `bucket` padded rows (fill ratio =
        rows / padded rows aggregated over batches)."""
        with self._lock:
            self._counters["batches_total"] += max(int(launches), 1)
            self._fill_rows += int(rows)
            self._fill_bucket += max(int(bucket), 1)

    def note_shape(self, key: Hashable, warmup: bool = False) -> bool:
        """Record one jit launch shape; returns True when it is new.

        New shapes during warmup count as `compiles_warmup`; new shapes
        afterwards are `compile_cache_misses` (the number the
        zero-cold-compile acceptance test asserts on)."""
        with self._lock:
            if key in self._shapes:
                self._counters["compile_cache_hits"] += 1
                return False
            self._shapes.add(key)
            self._counters["compiles_warmup" if warmup
                           else "compile_cache_misses"] += 1
            return True

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self._queue_depth = int(rows)

    # -- reading -------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            out = dict(self._counters)
            n = min(self._lat_n, self._window)
            lat = self._lat[:n].copy()
            out["queue_depth_rows"] = self._queue_depth
            out["batch_fill_ratio"] = (
                round(self._fill_rows / self._fill_bucket, 4)
                if self._fill_bucket else 0.0)
            out["latency_window"] = int(n)
        if n:
            p50, p95, p99 = np.percentile(lat, [50.0, 95.0, 99.0])
            out["latency_p50_ms"] = round(float(p50) * 1e3, 3)
            out["latency_p95_ms"] = round(float(p95) * 1e3, 3)
            out["latency_p99_ms"] = round(float(p99) * 1e3, 3)
        else:
            out["latency_p50_ms"] = out["latency_p95_ms"] = \
                out["latency_p99_ms"] = 0.0
        return out
