"""Serving runtime: model registry + shape-bucketed micro-batching.

Turns trained models into a long-lived inference service on top of the
device-resident forest predictor (`lightgbm_tpu/ops/predict.py`):

* `registry`  — load-once `name@version` model registry with LRU
  eviction, atomic hot-swap, and per-model warmup that pre-compiles
  every row-bucket launch shape,
* `batcher`   — micro-batching queue coalescing concurrent requests up
  to `serving_max_batch_rows` / `serving_max_wait_ms`, with bounded-
  queue admission control,
* `server`    — the thread-safe `ServingSession` front end and an
  optional stdlib HTTP/JSON endpoint (`python -m lightgbm_tpu serve`),
* `stats`     — rolling p50/p95/p99 latency, queue depth, batch fill,
  compile-cache hit/miss and shed counters.

Quick start::

    from lightgbm_tpu.serving import ServingSession

    session = ServingSession(params={"serving_max_batch_rows": 4096})
    session.load("churn", model_file="model.txt")   # packs + warms up
    y = session.predict("churn", X)                 # thread-safe
    session.stats()                                 # p99, fill, ...
"""

from .batcher import MicroBatcher, ServingQueueFull, ServingTimeout
from .registry import ModelEntry, ModelRegistry
from .server import ServingSession, serve_forever, serve_http
from .stats import CircuitBreaker, ServingStats

__all__ = [
    "CircuitBreaker",
    "MicroBatcher",
    "ModelEntry",
    "ModelRegistry",
    "ServingQueueFull",
    "ServingSession",
    "ServingStats",
    "ServingTimeout",
    "serve_forever",
    "serve_http",
]
