"""Serving runtime: model registry + shape-bucketed micro-batching.

Turns trained models into a long-lived inference service on top of the
device-resident forest predictor (`lightgbm_tpu/ops/predict.py`):

* `registry`  — load-once `name@version` model registry with LRU
  eviction, atomic hot-swap, per-entry health/breaker state, and
  per-model warmup that pre-compiles every row-bucket launch shape,
* `batcher`   — micro-batching queue coalescing concurrent requests up
  to `serving_max_batch_rows` under the ADAPTIVE coalescing window,
  with bounded-queue admission control, in-queue deadline expiry, a
  dispatch watchdog, and device failover onto the native walker,
* `admission` — AIMD admission controller against `serving_slo_ms`
  (priority-class sheds, 429/503 + Retry-After, drain gate),
* `server`    — the thread-safe `ServingSession` front end and an
  optional stdlib HTTP/JSON endpoint (`python -m lightgbm_tpu serve`)
  with `POST /drain` + SIGTERM drain lifecycle,
* `stats`     — rolling p50/p95/p99 latency, queue depth, batch fill,
  compile-cache hit/miss, shed/expiry/failover counters.

Models that carry a ``tpu_feature_profile:`` trailer additionally get a
per-model drift monitor (`obs/modelhealth.py`): sampled serving traffic
is binned through the TRAINING mappers and compared against the
captured profile (per-feature PSI/JS, NaN/unseen-category rates, raw-
score-histogram divergence), exposed as ``GET /drift`` JSON and
``lgbm_drift_*`` gauges on ``GET /metrics``, with a flight-recorder
event past ``serving_drift_psi_warn``.

Quick start::

    from lightgbm_tpu.serving import ServingSession

    session = ServingSession(params={"serving_max_batch_rows": 4096})
    session.load("churn", model_file="model.txt")   # packs + warms up
    y = session.predict("churn", X)                 # thread-safe
    session.stats()                                 # p99, fill, ...
"""

from .admission import (AdmissionController, ServingDraining,
                        ServingOverloaded)
from .batcher import (MicroBatcher, ServingExpired, ServingQueueFull,
                      ServingTimeout)
from .registry import ModelEntry, ModelRegistry
from .server import ServingSession, serve_forever, serve_http
from .stats import CircuitBreaker, ServingStats

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "MicroBatcher",
    "ModelEntry",
    "ModelRegistry",
    "ServingDraining",
    "ServingExpired",
    "ServingOverloaded",
    "ServingQueueFull",
    "ServingSession",
    "ServingStats",
    "ServingTimeout",
    "serve_forever",
    "serve_http",
]
