"""Model registry: load-once, device-resident models keyed `name@version`.

A model is loaded ONCE into a `Booster` + packed device forest
(`gbdt._packed_forest`) and then served read-only.  The registry adds
the runtime discipline around that:

* **versioning / hot-swap** — every load gets a `name@version` key and
  atomically flips the bare-`name` alias to it; in-flight requests on
  the old version finish against their resolved entry, new requests see
  the new one.  Old versions stay addressable by full key until evicted.
* **LRU eviction** — past `serving_max_models` resident entries the
  least-recently-resolved non-current version is dropped (current
  aliases are only evicted when nothing else is left).
* **warmup** — at load time every `row_bucket` launch shape a request of
  1..serving_max_batch_rows rows can produce is pre-compiled, so the
  steady state never pays a cold jit (`stats.compile_cache_misses`
  stays 0).
* **fallback** — a device-path failure mid-request falls back to the
  native host walker for that batch and is counted, not raised.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..config import Config, parse_tristate
from ..ops.predict import (_depth_bucket, check_serving_precision,
                           forest_class_scores, predict_row_buckets,
                           quantize_tables, row_bucket)
from ..utils import faultline, lockcheck, membudget
from ..utils.log import Log
from . import aot
from .placement import PlacementTable, Replica, resolve_serving_devices
from .stats import CircuitBreaker, ServingStats


class ModelEntry:
    """One resident model: booster + device tables + launch accounting."""

    def __init__(self, name: str, version: str, booster, config: Config,
                 stats: ServingStats, devices=None):
        self.name = name
        self.version = version
        self.key = f"{name}@{version}"
        self.booster = booster
        self.stats = stats
        drv = booster._driver
        drv._materialize()
        self.num_feature = booster.num_feature()
        # fleet placement (ISSUE 19): the device set this entry
        # replicates onto (None/[] = the process default device only,
        # the pre-fleet behavior direct constructions get)
        self.precision = check_serving_precision(
            str(config.serving_table_precision))
        self.devices = list(devices) if devices else None
        self.replicas: List[Replica] = []
        # tree count the AOT executables were compiled for (-1 = no AOT)
        self._aot_total = -1
        # the driver's own bucket policy governs every launch this entry
        # makes, so warmup must enumerate with the SAME ladder
        self.policy = drv.bucket_policy()
        self.max_batch_rows = int(config.serving_max_batch_rows)
        # serving pins the device predictor: 'auto' (native walker on CPU
        # hosts) would defeat the bounded-compile/warmup contract, so it
        # promotes to 'true' — per predict CALL (kwargs override), never
        # by mutating the adopted booster's own params; an explicit
        # 'false' stays respected
        mode = parse_tristate(booster.params.get("tpu_predict_device",
                                                 "auto"))
        if mode == "auto":
            mode = "true"
        self.device_on = (mode == "true"
                          and drv._pred_context() is not None
                          and booster.num_trees() > 0)
        self.hbm_bytes = 0
        # per-launch scratch ([rows, F] bins + [k, rows] scores) this
        # entry's largest dispatch allocates transiently — dispatches
        # are serialized, so the budget wall reserves the MAX across
        # entries, not the sum
        self.scratch_bytes = 0
        # set by the registry after construction: a dispatch-path OOM
        # reports here so sustained pressure can evict cold models
        # before the next dispatch OOMs too
        self.pressure_cb = None
        self._k = max(drv.num_tree_per_iteration, 1)
        self._depth = 1
        self.hbm_total_bytes = 0
        if self.device_on:
            import jax

            rows = min(self.max_batch_rows, self.chunk)
            self.scratch_bytes = rows * (self.num_feature * 4
                                         + self._k * 4)
            ctx = drv._pred_context()
            devs = self.devices or [jax.local_devices()[0]]
            breaker_kw = dict(
                threshold=int(config.serving_breaker_failures),
                cooldown_s=float(config.serving_breaker_cooldown_ms) / 1e3,
                stats=stats)
            host_q = None  # quantized host pack, built once, placed N×
            for i, dev in enumerate(devs):
                # guarded upload (ISSUE 15): an allocation failure here
                # is classified and named — and carries the DEVICE
                # index, so `device_alloc` chaos can target one replica
                # — instead of crashing the load as an anonymous
                # XlaRuntimeError; the registry retries after eviction,
                # then refuses with 507
                with membudget.oom_guard("registry_load", model=self.key,
                                         device=i):
                    if i == 0 and self.precision == "f32":
                        # the driver's own cached upload (default
                        # device): replica 0 at full precision shares
                        # it, so the pre-fleet single-device load pays
                        # exactly one upload, same as before
                        pf = drv._packed_forest()
                        self._depth = pf.depth
                        tables = pf.device()
                        meta = ctx.meta_dev()
                    else:
                        if host_q is None:
                            pf = drv._packed_forest()
                            self._depth = pf.depth
                            host_q = quantize_tables(pf.host(),
                                                     self.precision)
                        tables = {kk: jax.device_put(v, dev)
                                  for kk, v in host_q.items()}
                        meta = tuple(jax.device_put(m, dev)
                                     for m in ctx.meta_dev())
                    self.replicas.append(
                        Replica(i, dev, tables, meta,
                                CircuitBreaker(**breaker_kw)))
            # what this model costs on EACH device: the full packed
            # (possibly quantized) tables — replicas retain every tree
            # regardless of the num_iteration a request later slices
            # to.  `hbm_bytes` stays the PER-DEVICE unit every budget
            # formula prices in (the serving budget bounds one device's
            # HBM; replication multiplies fleet bytes, not per-device
            # pressure); `hbm_total_bytes` is the fleet-wide sum the
            # describe()/bench surfaces report
            self.hbm_bytes = self.replicas[0].nbytes
            self.hbm_total_bytes = sum(r.nbytes for r in self.replicas)
            self._setup_aot(config)
        # the gauge is set by ModelRegistry.load's registration block,
        # not here: a load that fails after construction (warmup error)
        # must not leave a phantom per-model series
        # drift monitor (ISSUE 14): models carrying a
        # tpu_feature_profile: trailer get sampled input/score drift
        # tracking against their training profile.  The tap is one
        # bounded row copy per predict; binning + PSI/JS run at scrape
        # time (GET /drift, GET /metrics) — zero device programs, zero
        # work when the profile is absent or sampling is off
        self.drift = None
        sample_rows = int(config.serving_drift_sample_rows)
        profile = drv.health_profile()
        if profile is not None and sample_rows > 0 \
                and drv._pred_context() is not None:
            from ..obs.modelhealth import DriftMonitor

            ctx = drv._pred_context()
            self.drift = DriftMonitor(
                profile, ctx.mappers, sample_rows=sample_rows,
                psi_warn=float(config.serving_drift_psi_warn),
                model=self.key, stats=stats,
                num_feature=self.num_feature,
                # raw scores via the host walker: matches the profile's
                # raw-score histogram on every objective, and the
                # scrape path may not steal device time from dispatch
                score_fn=lambda Xs: drv.predict_raw(Xs, -1))
        # circuit breaker on the device path: threshold failures open it
        # (requests short-circuit to a sibling replica, then the native
        # walker), a timed half-open probe retries the device path.
        # With replicas the entry-level breaker IS replica 0's (the
        # pre-fleet single-breaker API keeps working)
        if self.replicas:
            self.breaker = self.replicas[0].breaker
        else:
            self.breaker = CircuitBreaker(
                threshold=int(config.serving_breaker_failures),
                cooldown_s=float(config.serving_breaker_cooldown_ms) / 1e3,
                stats=stats)

    def _setup_aot(self, config: Config) -> None:
        """AOT-compiled cold start (ISSUE 19): at load time, every
        (replica, row-bucket) launch of the default-num_iteration
        predict either deserializes from the AOT cache (`aot_cache_hits`
        — ZERO new compiled programs; the executables never enter the
        jit cache, so the compile ledger proves the cold start) or warm-
        compiles via lower().compile() and is serialized for the next
        cold process (`aot_cache_misses`).  Any per-bucket failure
        degrades to the jitted path with a logged warning — a bad cache
        entry can slow a load, never fail one."""
        dirpath = aot.cache_dir(config)
        if dirpath is None:
            return
        drv = self.booster._driver
        ni = self.default_num_iteration()
        total, _ = drv._model_subset(-1 if ni is None else ni)
        if total <= 0:
            return
        sig = self.warm_signature()
        buckets = predict_row_buckets(self.max_batch_rows, self.chunk,
                                      policy=self.policy)
        depth_b = _depth_bucket(self._depth, self.policy)
        for replica in self.replicas:
            sh = aot.signature_hash(sig, replica.device)
            tables = replica.sliced(total)
            for b in buckets:
                path = aot.bucket_path(dirpath, sh, replica.index, b)
                exe = None
                if os.path.exists(path):
                    try:
                        exe = aot.load_bucket(path)
                        self.stats.count("aot_cache_hits")
                    except Exception as exc:
                        Log.warning(
                            f"AOT cache entry {os.path.basename(path)} "
                            f"for {self.key} rejected ({exc}); falling "
                            "back to a warm compile")
                if exe is None:
                    self.stats.count("aot_cache_misses")
                    try:
                        exe = aot.compile_bucket(
                            tables, self.num_feature, b,
                            replica.meta_dev, depth_b, self._k)
                        aot.save_bucket(path, exe)
                    except Exception as exc:
                        Log.warning(
                            f"AOT compile of bucket {b} on device "
                            f"{replica.index} for {self.key} failed "
                            f"({exc}); this bucket serves via the "
                            "jitted path")
                        continue
                replica.aot[b] = exe
        self._aot_total = total

    # ------------------------------------------------------------------
    @property
    def chunk(self) -> int:
        """The driver's LIVE predict chunk — read dynamically, never
        cached: an OOM-driven shrink (gbdt._shrink_predict_chunk) must
        flow into this entry's launch-bucket accounting immediately, or
        batch_fill_ratio / the shape series / the scratch reservation
        would report the pre-shrink launches forever."""
        return self.booster._driver.predict_chunk_rows()

    def default_num_iteration(self) -> int:
        """The num_iteration a None request resolves to — mirrors
        Booster.predict's best_iteration default, and is what warmup
        must pre-compile (an early-stopped model's sliced tree tables
        are a different jit shape than the full forest's)."""
        bi = self.booster.best_iteration
        return bi if bi is not None and bi >= 0 else -1

    def warm_signature(self):
        """Everything that keys this entry's predict programs: two
        entries with equal signatures trigger byte-identical jit cache
        keys for every warmup launch, so the registry runs the warmup
        sweep ONCE per signature — loading a second same-shaped model
        adds zero compiled programs AND zero warmup wall."""
        if not self.device_on:
            return None
        drv = self.booster._driver
        ni = self.default_num_iteration()
        total, _ = drv._model_subset(-1 if ni is None else ni)
        # shapes+dtypes off replica 0's resident tables (no re-upload):
        # quantized precisions change the dtypes, so each precision
        # keys its own programs AND its own AOT cache files
        tables = self.replicas[0].sliced(total)
        shapes = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                              for k, v in tables.items()))
        return (self.chunk, self.max_batch_rows, self.policy,
                self.num_feature, self._k,
                _depth_bucket(self._depth, self.policy),
                shapes)

    def warmup(self, precompiled: bool = False) -> int:
        """Pre-compile every launch shape; returns the bucket count.

        precompiled=True (another resident entry already warmed an equal
        `warm_signature`) skips the device launches and only registers
        the shapes with the stats accounting — the programs exist in the
        jit cache, so this entry's first real predicts are warm."""
        if not self.device_on:
            return 0
        buckets = predict_row_buckets(self.max_batch_rows, self.chunk,
                                      policy=self.policy)
        ni = self.default_num_iteration()
        for replica in self.replicas:
            # a replica whose every bucket deserialized from the AOT
            # cache needs NO warmup launches: the executables exist
            # outside the jit cache, so the first served batch runs
            # with zero new compiled programs (the cold-start contract)
            aot_ready = (self._aot_total >= 0
                         and all(b in replica.aot for b in buckets))
            for b in buckets:
                if precompiled or aot_ready:
                    # aot_ready shapes charge NO compile ledger: the
                    # executable was deserialized, not compiled
                    self.stats.note_shape(
                        self._shape_key(ni, b, replica.index),
                        warmup=True, compiled=not aot_ready)
                else:
                    self.predict(
                        np.zeros((b, self.num_feature), np.float64),
                        num_iteration=ni, warmup=True,
                        device_index=replica.index)
        return len(buckets)

    def _shape_key(self, ni: int, bucket: int, index: int):
        """Launch-shape accounting key: single-device entries keep the
        pre-fleet (key, ni, bucket) form; replicated entries key per
        device (each device's jit/AOT program is its own compile)."""
        if len(self.replicas) <= 1:
            return (self.key, ni, bucket)
        return (self.key, ni, bucket, index)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                num_iteration: int = -1, warmup: bool = False,
                device_index: Optional[int] = None) -> np.ndarray:
        """The batch runner: one device predict with launch-shape
        accounting.  `device_index` is the batcher worker the batch
        routed to (None = first routable replica).  A device failure
        serves THIS batch via a SIBLING replica (counted
        `replica_failovers`, the failed device's breaker fed) before
        degrading to the native host walker; past the failure threshold
        a replica's breaker opens and requests route around it (zero
        device attempts there) until a timed half-open probe finds that
        device healthy again."""
        ni = -1 if num_iteration is None else int(num_iteration)
        if not warmup and self.drift is not None:
            # drift tap BEFORE any path split: input drift is a property
            # of the request, not of which predictor served it.  One
            # stride-sampled row copy + a GIL-atomic deque append — the
            # accumulation itself runs at scrape time, off this worker
            self.drift.tap(X)
        if not self.device_on:
            if not warmup:
                self.stats.note_batch(X.shape[0], X.shape[0])
            return self._native_predict(X, raw_score, ni)
        n = int(X.shape[0])
        bucket = row_bucket(n, self.chunk, policy=self.policy)
        order = self._route(device_index, warmup)
        if not order:
            # every replica's breaker is open: no device launch happens,
            # so account this batch like the native path (unpadded rows)
            self.stats.note_batch(n, n)
            return self._native_predict(X, raw_score, ni)
        if not warmup:
            # a batch wider than the predict chunk runs ceil(n/chunk)
            # padded launches inside the chunked scorer — account them
            # all, or batch_fill_ratio would exceed 1.0
            launches = -(-n // self.chunk) if n > self.chunk else 1
            self.stats.note_batch(n, launches * bucket, launches=launches)
        self.stats.note_shape(self._shape_key(ni, bucket, order[0].index),
                              warmup=warmup)
        failed: List[Replica] = []
        for replica in order:
            # generation snapshot: if the dispatch watchdog abandons
            # this call and records a failure while it runs, the
            # success below becomes stale and must not reset/close the
            # breaker
            gen = replica.breaker.generation
            # device walls are unbounded from the host's view: entering
            # one holding any serving/obs lock would stall every thread
            # queued on it (lockcheck flags it under tests)
            lockcheck.check_dispatch("registry.predict")
            try:
                out = self._dispatch_replica(replica, X, raw_score, ni,
                                             warmup)
            except Exception as exc:
                # route through the membudget classifier FIRST: a
                # dispatch OOM is a pressure signal (count it, let the
                # registry evict cold models) before it is a device
                # failure
                if membudget.is_oom_error(exc):
                    if warmup:
                        # warmup must NOT silently walk a model that
                        # cannot fit: the load path (which owns its own
                        # eviction + retry + models_refused_hbm
                        # accounting) retries or refuses with 507
                        raise
                    self.stats.count("dispatch_oom")
                    if self.pressure_cb is not None:
                        try:
                            self.pressure_cb(self.key)
                        except Exception:  # pragma: no cover - defensive
                            pass
                if warmup:
                    raise
                failed.append(replica)
                continue  # next replica in routing order
            if not warmup:
                # the failed siblings' breakers are fed only once the
                # batch actually lands somewhere device-side — a data
                # error that raises on EVERY path must not open
                # breakers (the walker below re-raises it first)
                for f in failed:
                    f.breaker.record_failure()
                if failed:
                    self.stats.count("replica_failovers")
                replica.breaker.record_success(gen)
            return out
        # every attempted replica raised: serve via the native walker.
        # A caller/data error raises identically here and propagates
        # WITHOUT feeding any breaker or fallback counter — failing
        # over would mask a 400 and poison the device-failure signal
        out = self._native_predict(X, raw_score, ni)
        self.stats.count("device_fallbacks")
        if not warmup:
            for f in failed:
                f.breaker.record_failure()
        return out

    def _route(self, device_index: Optional[int],
               warmup: bool) -> List[Replica]:
        """Replica attempt order.  A pinned `device_index` (the batcher
        worker the batch landed on) goes first with its siblings as
        failover; warmup pins EXACTLY one replica (its compiles must
        land on its device, and warmup errors must raise, not roam).
        `allow()` is the consuming breaker gate — one probe slot per
        actual attempt."""
        reps = self.replicas
        if not reps:
            return []
        if device_index is not None:
            pinned = reps[int(device_index) % len(reps)]
            if warmup:
                return [pinned]
            rest = [r for r in reps
                    if r is not pinned and r.breaker.allow()]
            if pinned.breaker.allow():
                return [pinned] + rest
            return rest
        if warmup:
            return [reps[0]]
        return [r for r in reps if r.breaker.allow()]

    def _dispatch_replica(self, replica: Replica, X: np.ndarray,
                          raw_score: bool, ni: int,
                          warmup: bool) -> np.ndarray:
        """One device attempt on one replica, chaos- and OOM-guarded
        with the device coordinate attached (single-device fault
        targeting: `where={"device": k}`)."""
        if not warmup:
            action = faultline.fire("serve_dispatch", model=self.key,
                                    device=replica.index)
            if action == "hang":
                # simulate a wedged device stream: never return.  The
                # batcher's per-device dispatch watchdog
                # (serving_dispatch_timeout_ms) abandons this thread,
                # fails the batch over, and feeds the breaker; sibling
                # workers keep serving
                import time as _time

                _time.sleep(3600.0)
        with membudget.oom_guard(
                "registry_warmup" if warmup else "serve_dispatch",
                model=self.key, device=replica.index):
            if replica.index == 0 and self.precision == "f32" \
                    and not replica.aot:
                # the pre-fleet dispatch: booster.predict owns the
                # shrink ladder + chunked scorer on the default device
                return self.booster.predict(X, raw_score=raw_score,
                                            num_iteration=ni,
                                            device="tpu",
                                            tpu_predict_device="true")
            return self._replica_predict(replica, X, raw_score, ni)

    def _replica_predict(self, replica: Replica, X: np.ndarray,
                         raw_score: bool, ni: int) -> np.ndarray:
        drv = self.booster._driver
        total, div = drv._model_subset(ni)
        if total == 0:
            return self._native_predict(X, raw_score, ni)
        raw = self._replica_scores(replica, np.asarray(X, np.float64),
                                   total) / div
        return drv._finish_predict(raw, raw_score)

    def _replica_scores(self, replica: Replica, X: np.ndarray,
                        total: int) -> np.ndarray:
        """[k, n] f64 scores off ONE replica's resident tables, chunked
        over rows like gbdt._chunked_device_scores but pinned to the
        replica's device.  Buckets the AOT executables cover dispatch
        through them — zero jit-cache programs; everything else rides
        the jitted kernel (per-device programs, warmed at load).
        Quantized tables dequantize inside the kernel; accumulation is
        f64 on host either way, so the drift monitor and every score
        consumer see plain f32-dequantized scores."""
        import jax

        drv = self.booster._driver
        ctx = drv._pred_context()
        k = self._k
        n = int(X.shape[0])
        out = np.zeros((k, n), np.float64)
        tables = replica.sliced(total)
        aot_ok = total == self._aot_total
        lo = 0
        while lo < n:
            chunk = self.chunk
            hi = min(lo + chunk, n)
            rows = hi - lo
            faultline.fire("h2d_copy", rows=rows, device=replica.index)
            bins = ctx.bin_rows(X[lo:hi])
            target = (chunk if n > chunk
                      else row_bucket(rows, chunk, policy=self.policy))
            if rows < target:
                bins = np.concatenate(
                    [bins, np.zeros((target - rows, bins.shape[1]),
                                    np.int32)])
            bins_dev = jax.device_put(
                np.ascontiguousarray(bins.astype(np.int32)),
                replica.device)
            exe = replica.aot.get(target) if aot_ok else None
            if exe is not None:
                nb, db, mt = replica.meta_dev
                scores = exe(tables, bins_dev, nb, db, mt,
                             replica.scale_dev)
            else:
                scores = forest_class_scores(
                    tables, bins_dev, replica.meta_dev, k, self._depth,
                    policy=self.policy)
            out[:, lo:hi] = np.asarray(jax.device_get(scores),
                                       np.float64)[:, :rows]
            lo = hi
        return out

    def _native_predict(self, X: np.ndarray, raw_score: bool,
                        ni: int) -> np.ndarray:
        return self.booster.predict(X, raw_score=raw_score,
                                    num_iteration=ni, device="cpu")

    # -- failover hooks (the batcher's on_error / fallback pair) -------
    @property
    def healthy(self) -> bool:
        """False while EVERY replica's device-path breaker is OPEN
        (requests are short-circuiting to the native walker); a fleet
        with one live device is degraded, not unhealthy."""
        if self.replicas:
            return any(r.breaker.state != "open" for r in self.replicas)
        return self.breaker.state != "open"

    def replica_ok(self, index: int) -> bool:
        """The batcher router's NON-consuming device filter: True when
        replica `index` could take traffic right now (closed/half-open
        breaker, or open with the cooldown elapsed).  Deliberately not
        `allow()` — a routing peek must not consume half-open probe
        slots (the dispatch path's own allow() takes exactly one per
        attempt)."""
        if not self.replicas:
            return index == 0
        return self.replicas[int(index) % len(self.replicas)] \
            .breaker.routable

    def native_runner(self, raw_score: bool, ni: int):
        """The failover target: a pure host-walker runner for this
        entry — the 'healthy replica' of last resort.  The batcher
        re-runs a batch on it when the device dispatch raises or hangs,
        so riders get answers instead of the failure.  (Mesh replicas
        slot into this hook: a multi-device registry returns another
        device's runner here before degrading to the walker.)"""
        def run(Xb: np.ndarray) -> np.ndarray:
            return self._native_predict(Xb, raw_score, ni)
        return run

    def record_dispatch_error(self, exc: BaseException,
                              device: Optional[int] = None) -> bool:
        """Classify a dispatch failure for the batcher: True = device-
        path failure (feed THAT device's breaker, fail the batch over
        to the native runner); False = caller error (malformed rows
        raise identically on both paths — failing over would mask a 400
        as a fallback and poison the breaker signal)."""
        from ..utils.log import LightGBMError

        if isinstance(exc, (LightGBMError, ValueError, KeyError,
                            TypeError)):
            return False
        # device/XLA error or a hang promoted to ServingTimeout by the
        # dispatch watchdog: the breaker keeps later requests off that
        # device's path until a half-open probe finds it healthy
        breaker = self.breaker
        if device is not None and self.replicas:
            breaker = self.replicas[int(device)
                                    % len(self.replicas)].breaker
        breaker.record_failure()
        return True

    def describe(self) -> Dict:
        return {"key": self.key, "name": self.name, "version": self.version,
                "num_feature": self.num_feature,
                "num_trees": self.booster.num_trees(),
                "device": bool(self.device_on),
                "devices": len(self.replicas),
                "precision": self.precision,
                "hbm_bytes": int(self.hbm_bytes),
                "hbm_total_bytes": int(self.hbm_total_bytes),
                "aot_buckets": (len(self.replicas[0].aot)
                                if self.replicas else 0),
                "breaker": self.breaker.state,
                "breakers": {r.index: r.breaker.state
                             for r in self.replicas},
                "healthy": self.healthy,
                "drift_monitor": self.drift is not None}


class ModelRegistry:
    """name@version -> ModelEntry with LRU eviction and hot-swap."""

    def __init__(self, config: Optional[Config] = None,
                 stats: Optional[ServingStats] = None):
        self.config = config if config is not None else Config({})
        self.stats = stats if stats is not None else ServingStats()
        # fleet device set (ISSUE 19): resolved ONCE per registry; every
        # entry replicates onto it and the placement table tells the
        # batcher's router which worker indices hold which model
        self.devices = resolve_serving_devices(self.config)
        self.placement = PlacementTable()
        self._lock = lockcheck.make_rlock("serving.registry")
        self._entries: "OrderedDict[str, ModelEntry]" = OrderedDict()
        self._latest: Dict[str, str] = {}   # name -> current key
        self._counts: Dict[str, int] = {}   # name -> loads so far
        # warm signatures already compiled in this process: a second
        # same-shaped model load skips the warmup device launches
        self._warmed: set = set()

    # ------------------------------------------------------------------
    def load(self, name: str, model_file: Optional[str] = None,
             model_str: Optional[str] = None, booster=None,
             params: Optional[Dict] = None,
             version: Optional[str] = None) -> ModelEntry:
        """Load + warm a model, then atomically flip `name` to it.

        The expensive part (parse, pack, warmup compiles) runs OUTSIDE
        the registry lock: a hot-swap never blocks serving of the old
        version.  A user-supplied `booster` is adopted as-is (its
        tpu_predict_device param may be promoted to 'true')."""
        if "@" in name:
            raise ValueError("model name must not contain '@' "
                             "(reserved for name@version keys)")
        if booster is None:
            from ..booster import Booster

            merged = dict(params or {})
            if model_file is not None:
                booster = Booster(params=merged, model_file=model_file)
            elif model_str is not None:
                booster = Booster(params=merged, model_str=model_str)
            else:
                raise ValueError(
                    "load needs model_file=, model_str= or booster=")
        with self._lock:
            if version is not None:
                ver = str(version)
                # keep the implicit counter ahead of explicit NUMERIC
                # versions so a later version-less load never reuses (and
                # silently replaces) an existing name@N entry
                try:
                    self._counts[name] = max(self._counts.get(name, 0),
                                             int(ver))
                except ValueError:
                    pass
            else:
                self._counts[name] = self._counts.get(name, 0) + 1
                ver = str(self._counts[name])
        # HBM budget preflight (ISSUE 15): predicted packed-table +
        # launch-scratch bytes BEFORE any upload.  Over budget -> evict
        # cold models to make room; still over -> structured 507
        # refusal instead of warming into a device crash
        self._preflight_load(name, ver, booster)
        entry = self._build_entry(name, ver, booster)
        entry.pressure_cb = self._on_dispatch_oom
        with self._lock:
            # the AUTHORITATIVE budget wall, re-checked under the lock:
            # the pre-upload preflight read resident bytes without it,
            # so two concurrent over-half-budget loads could both pass
            # and jointly breach the wall — admission is serialized
            # here, where insertion is
            budget = self._budget()
            if budget is not None and entry.hbm_bytes:
                def over():
                    return self._admission_overflow_locked(
                        entry.key, entry.hbm_bytes,
                        entry.scratch_bytes, budget) > 0
                if over():
                    self._evict_cold_locked(lambda _f, _n: not over())
                if over():
                    self.stats.count("models_refused_hbm")
                    raise membudget.ServingMemoryExhausted(
                        f"loading model {entry.key} would put "
                        f"{self._resident_bytes_locked() + entry.hbm_bytes:,d} "
                        "resident device bytes (plus launch scratch) "
                        f"against the {budget:,d}-byte serving HBM "
                        "budget (a concurrent load took the "
                        "headroom); retry or raise the budget",
                        site="registry_load", info={"model": name})
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            self.placement.place(entry.key,
                                 [r.index for r in entry.replicas])
            self.stats.set_model_hbm(entry.key, entry.hbm_bytes)
            # a reloaded key re-arms drift publishing (clear_drift
            # tombstones it on unload/eviction so an in-flight scrape
            # cannot resurrect a departed model's gauges)
            self.stats.reopen_drift(entry.key)
            # atomic flip (hot-swap) — but never BACKWARDS: concurrent
            # loads finish warmup in arbitrary order, and last-finisher-
            # wins would let a stale version steal the alias
            if not self._version_newer(self._latest.get(name), ver):
                self._latest[name] = entry.key
            self.stats.count("models_loaded")
            self._evict_locked()
        return entry

    # -- memory pressure (ISSUE 15) ------------------------------------
    def _budget(self) -> Optional[int]:
        return membudget.serving_budget_bytes(self.config)

    def _resident_bytes_locked(self) -> int:
        return sum(e.hbm_bytes for e in self._entries.values())

    def _admission_overflow_locked(self, key: str, new_tables: int,
                                   new_scratch: int, budget: int) -> int:
        """THE serving admission formula — bytes over budget (<= 0
        fits), shared by the pre-upload preflight and the under-lock
        registration wall so the two can never drift apart (a mismatch
        would let an uncontended load pass preflight, burn the upload +
        warmup, then be refused at the wall): resident tables — minus a
        same-`key` entry about to be replaced IN PLACE, whose bytes
        leave as the new ones land — plus the new tables, plus the MAX
        launch scratch across entries (dispatches serialize, so scratch
        reserves once)."""
        resident = sum(e.hbm_bytes for e in self._entries.values()
                       if e.key != key)
        scratch = max([e.scratch_bytes for e in self._entries.values()
                       if e.key != key] + [new_scratch])
        return resident + new_tables + scratch - budget

    def _preflight_load(self, name: str, ver: str, booster) -> None:
        """Refuse (507) a load whose PREDICTED device bytes cannot fit
        the serving budget, evicting cold models first — the planner
        runs off the host pack, so nothing touches HBM before the
        verdict.  Applies `_admission_overflow_locked`, the SAME
        formula the under-lock wall re-checks at registration."""
        budget = self._budget()
        if budget is None:
            return
        membudget.publish_budget_gauge(budget, "serving")
        plan = membudget.plan_model_load(booster, self.config)
        if plan is None:
            return  # no device path: nothing lands in HBM
        tables = plan.components.get("packed_tables", 0)
        if tables > 0:
            key = f"{name}@{ver}"
            new_scratch = plan.components.get("launch_scratch", 0)
            with self._lock:
                overflow = self._admission_overflow_locked(
                    key, tables, new_scratch, budget)
            if overflow > 0:
                self.relieve_pressure(need_bytes=overflow)
                with self._lock:
                    overflow = self._admission_overflow_locked(
                        key, tables, new_scratch, budget)
            if overflow > 0:
                self.stats.count("models_refused_hbm")
                from ..obs import flightrecorder

                with self._lock:
                    resident = self._resident_bytes_locked()
                flightrecorder.note("oom", "load_refused", model=name,
                                    predicted=plan.total,
                                    resident=resident, budget=budget)
                raise membudget.ServingMemoryExhausted(
                    plan.refuse_message(
                        f"loading model {name!r} "
                        f"({resident:,d} bytes already resident)"),
                    site="registry_load",
                    info={"model": name, "resident_bytes": resident})

    def _build_entry(self, name: str, ver: str, booster) -> ModelEntry:
        """Construct + warm the entry; a classified OOM during the
        upload or warmup evicts cold models and retries ONCE, then
        refuses with the structured 507 — an under-budget prediction
        that still OOMs (fragmentation, co-tenants) must not crash the
        process or silently admit a walker-only model."""
        for attempt in (0, 1):
            try:
                entry = ModelEntry(name, ver, booster, self.config,
                                   self.stats, devices=self.devices)
                if bool(self.config.serving_warmup):
                    # dedupe warmup compiles across models sharing a
                    # launch-shape signature: the jit cache is process-
                    # wide, so a second same-shaped model's sweep would
                    # only re-execute programs that already exist
                    sig = entry.warm_signature()
                    with self._lock:
                        seen = sig is not None and sig in self._warmed
                    entry.warmup(precompiled=seen)
                    # marked warmed only AFTER the sweep succeeds: a
                    # failed (or concurrent, still-compiling) warmup
                    # must not make future same-shaped loads skip
                    # theirs and serve cold compiles
                    if sig is not None:
                        with self._lock:
                            self._warmed.add(sig)
                return entry
            except membudget.DeviceOutOfMemory as exc:
                freed = self.relieve_pressure()
                if attempt == 1 or not freed:
                    self.stats.count("models_refused_hbm")
                    raise membudget.ServingMemoryExhausted(
                        f"loading model {name!r} ran out of device "
                        f"memory at {exc.site!r} and eviction could "
                        "not free enough; refuse instead of serving a "
                        "model whose every dispatch would OOM",
                        site=exc.site, info=dict(exc.info)) from exc
                Log.warning(
                    f"device OOM at {exc.site!r} while loading "
                    f"{name!r}: evicted {freed} cold device bytes, "
                    "retrying the load once")

    def _on_dispatch_oom(self, key: str) -> None:
        """A dispatch-path OOM reported by an entry: sustained pressure
        — evict a cold model so the NEXT dispatch has headroom (the
        failing batch itself was already served by the walker)."""
        freed = self.relieve_pressure()
        if freed:
            Log.warning(f"dispatch OOM on {key}: evicted {freed} cold "
                        "device bytes under memory pressure")

    def relieve_pressure(self, need_bytes: int = 0) -> int:
        """Evict cold (non-current) LRU models until `need_bytes` are
        freed (0 = exactly one victim); returns the bytes actually
        freed.  Current aliases are never evicted here — shedding the
        model a caller is actively resolving trades one failure for
        another."""
        with self._lock:
            if need_bytes > 0:
                done = lambda freed, n: freed >= need_bytes  # noqa: E731
            else:
                done = lambda freed, n: n >= 1               # noqa: E731
            freed = self._evict_cold_locked(done)
            self._publish_pressure_locked()
        return freed

    def _evict_cold_locked(self, done) -> int:
        """Evict cold (non-current) DEVICE-BACKED LRU entries until
        `done(freed_bytes, victims)` or none remain — the ONE eviction
        body every pressure path shares (the per-victim bookkeeping
        must never skew between them).  Zero-byte (walker-only) entries
        are never pressure victims: evicting them frees no HBM, and a
        byte-driven sweep would otherwise clear every one of them for
        nothing (the serving_max_models count cap owns their slots)."""
        freed = 0
        n = 0
        current = set(self._latest.values())
        while not done(freed, n):
            victim = next((k for k, e in self._entries.items()
                           if k not in current and e.hbm_bytes > 0),
                          None)
            if victim is None:
                break
            got = int(self._entries[victim].hbm_bytes)
            freed += got
            n += 1
            del self._entries[victim]
            self.placement.remove(victim)
            self.stats.count("models_evicted")
            self.stats.count("evictions_pressure")
            self.stats.clear_model_hbm(victim)
            self.stats.clear_drift(victim)
            Log.info(f"serving registry evicted {victim} under memory "
                     f"pressure: freed {got} device bytes")
        return freed

    def _publish_pressure_locked(self) -> None:
        total = self._resident_bytes_locked()
        self.stats.set_total_hbm(total)
        budget = self._budget()
        if budget:
            self.stats.set_hbm_pressure(total / budget)
        # per-DEVICE residency, zeros included: an eviction of a
        # replicated model must visibly free bytes on EVERY device
        per_dev = {i: 0 for i in range(max(len(self.devices), 1))}
        for e in self._entries.values():
            for r in e.replicas:
                per_dev[r.index] = per_dev.get(r.index, 0) + r.nbytes
        for i, nbytes in per_dev.items():
            self.stats.set_device_hbm(i, nbytes)

    @staticmethod
    def _version_newer(current_key: Optional[str], candidate: str) -> bool:
        """True when the currently-aliased version outranks `candidate`
        (numeric compare when both versions are numeric, else the flip
        always proceeds — explicit string versions are caller-ordered)."""
        if current_key is None:
            return False
        try:
            return int(current_key.rsplit("@", 1)[1]) > int(candidate)
        except (ValueError, IndexError):
            return False

    def _evict_locked(self) -> None:
        cap = max(int(self.config.serving_max_models), 1)
        while len(self._entries) > cap:
            current = set(self._latest.values())
            victim = next((k for k in self._entries if k not in current),
                          None)
            if victim is None:
                # every entry is someone's current version: retire the
                # least-recently-used name entirely
                victim = next(iter(self._entries))
                self._latest = {n: k for n, k in self._latest.items()
                                if k != victim}
            freed = int(self._entries[victim].hbm_bytes)
            del self._entries[victim]
            self.placement.remove(victim)
            self.stats.count("models_evicted")
            self.stats.clear_model_hbm(victim)
            self.stats.clear_drift(victim)
            Log.info(f"serving registry evicted {victim}: freed {freed} "
                     "device bytes "
                     f"({len(self._entries)}/{cap} models resident)")
        # sustained byte pressure (ISSUE 15): past the pressure
        # fraction of the serving HBM budget, cold (non-current) LRU
        # models leave ahead of demand — before a dispatch has to OOM
        budget = self._budget()
        if budget:
            frac = float(self.config.serving_hbm_pressure_frac)
            threshold = int(budget * max(min(frac, 1.0), 0.05))
            self._evict_cold_locked(
                lambda _f, _n: self._resident_bytes_locked() <= threshold)
        self._publish_pressure_locked()

    # ------------------------------------------------------------------
    def admission_headroom(self, new_tables: int,
                           new_scratch: int = 0) -> Optional[int]:
        """Serving-budget bytes left for a NEW entry of the given size
        (negative = would not fit; None = no budget configured, always
        admissible).  The continual controller preflights a candidate
        retrain against this BEFORE spending the training wall: the
        two-generation swap needs candidate+live resident together, so
        a candidate that cannot be admitted defers the retrain instead
        of OOM-crashing the shadow load."""
        budget = self._budget()
        if budget is None:
            return None
        with self._lock:
            return -self._admission_overflow_locked(
                "", int(new_tables), int(new_scratch), budget)

    def promote(self, name: str, key: str) -> Optional[str]:
        """Atomically re-alias bare `name` to an ALREADY-RESIDENT entry
        (shadow-gated promotion, ISSUE 17): the candidate was loaded,
        warmed and scored under a shadow name; the flip here is one
        dict store under the registry lock — in-flight requests that
        already resolved the old entry finish against it, new resolves
        see the promoted one.  Zero requests are dropped or double-
        answered because nothing else changes.  Returns the previously
        aliased key (None when `name` had no alias) so the caller can
        roll back with another `promote(name, prev_key)`."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                raise KeyError(
                    f"no resident entry {key!r} to promote as {name!r}")
            prev = self._latest.get(name)
            self._latest[name] = key
            self._entries.move_to_end(key)  # LRU touch: now current
            return prev

    def resolve(self, name: str) -> ModelEntry:
        """`name` (current version) or exact `name@version` -> entry."""
        with self._lock:
            key = self._latest.get(name, name)
            entry = self._entries.get(key)
            if entry is None:
                raise KeyError(f"no model {name!r} in the serving registry")
            self._entries.move_to_end(key)  # LRU touch
            return entry

    def unload(self, name: str) -> None:
        """Drop one version (`name@version`) or, for a bare name, EVERY
        resident version of it — an operator unload must actually
        release the packed device tables, not just the current alias.
        Unloading the CURRENT version re-aliases the name to its newest
        surviving version (the rollback workflow), rather than leaving
        resident versions unreachable by bare name."""
        with self._lock:
            if "@" in name:
                victims = [name]
            else:
                victims = [k for k, e in self._entries.items()
                           if e.name == name]
                alias = self._latest.get(name)
                if alias is not None and alias not in victims:
                    # a cross-name promotion (a shadow entry aliased
                    # under this name) must leave with the name it
                    # serves, not survive as an unreachable resident
                    victims.append(alias)
            removed = [self._entries.pop(k) for k in victims
                       if k in self._entries]
            for e in removed:
                self.placement.remove(e.key)
                self.stats.clear_model_hbm(e.key)
                self.stats.clear_drift(e.key)
                if e.hbm_bytes:
                    Log.info(f"serving registry unloaded {e.key}: freed "
                             f"{int(e.hbm_bytes)} device bytes")
            self._publish_pressure_locked()
            gone = set(victims)
            self._latest = {n: k for n, k in self._latest.items()
                            if k not in gone and n != name}
            for e in removed:
                if e.name in self._latest:
                    continue
                survivors = [k for k, s in self._entries.items()
                             if s.name == e.name]
                if survivors:
                    self._latest[e.name] = max(
                        survivors, key=self._version_rank)

    @staticmethod
    def _version_rank(key: str):
        ver = key.rsplit("@", 1)[1]
        try:
            return (1, int(ver), ver)
        except ValueError:
            return (0, 0, ver)

    def models(self) -> List[Dict]:
        with self._lock:
            current = {k: n for n, k in self._latest.items()}
            return [{**e.describe(), "current": e.key in current}
                    for e in self._entries.values()]

    def entries(self) -> List[ModelEntry]:
        """Resident entries, snapshot under the lock (no LRU touch) —
        the drift scrape iterates this without blocking loads."""
        with self._lock:
            return list(self._entries.values())
