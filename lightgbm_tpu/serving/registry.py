"""Model registry: load-once, device-resident models keyed `name@version`.

A model is loaded ONCE into a `Booster` + packed device forest
(`gbdt._packed_forest`) and then served read-only.  The registry adds
the runtime discipline around that:

* **versioning / hot-swap** — every load gets a `name@version` key and
  atomically flips the bare-`name` alias to it; in-flight requests on
  the old version finish against their resolved entry, new requests see
  the new one.  Old versions stay addressable by full key until evicted.
* **LRU eviction** — past `serving_max_models` resident entries the
  least-recently-resolved non-current version is dropped (current
  aliases are only evicted when nothing else is left).
* **warmup** — at load time every `row_bucket` launch shape a request of
  1..serving_max_batch_rows rows can produce is pre-compiled, so the
  steady state never pays a cold jit (`stats.compile_cache_misses`
  stays 0).
* **fallback** — a device-path failure mid-request falls back to the
  native host walker for that batch and is counted, not raised.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..config import Config, parse_tristate
from ..ops.predict import _depth_bucket, predict_row_buckets, row_bucket
from ..utils import faultline, lockcheck, membudget
from ..utils.log import Log
from .stats import CircuitBreaker, ServingStats


class ModelEntry:
    """One resident model: booster + device tables + launch accounting."""

    def __init__(self, name: str, version: str, booster, config: Config,
                 stats: ServingStats):
        self.name = name
        self.version = version
        self.key = f"{name}@{version}"
        self.booster = booster
        self.stats = stats
        drv = booster._driver
        drv._materialize()
        self.num_feature = booster.num_feature()
        # the driver's own bucket policy governs every launch this entry
        # makes, so warmup must enumerate with the SAME ladder
        self.policy = drv.bucket_policy()
        self.max_batch_rows = int(config.serving_max_batch_rows)
        # serving pins the device predictor: 'auto' (native walker on CPU
        # hosts) would defeat the bounded-compile/warmup contract, so it
        # promotes to 'true' — per predict CALL (kwargs override), never
        # by mutating the adopted booster's own params; an explicit
        # 'false' stays respected
        mode = parse_tristate(booster.params.get("tpu_predict_device",
                                                 "auto"))
        if mode == "auto":
            mode = "true"
        self.device_on = (mode == "true"
                          and drv._pred_context() is not None
                          and booster.num_trees() > 0)
        self.hbm_bytes = 0
        # per-launch scratch ([rows, F] bins + [k, rows] scores) this
        # entry's largest dispatch allocates transiently — dispatches
        # are serialized, so the budget wall reserves the MAX across
        # entries, not the sum
        self.scratch_bytes = 0
        # set by the registry after construction: a dispatch-path OOM
        # reports here so sustained pressure can evict cold models
        # before the next dispatch OOMs too
        self.pressure_cb = None
        if self.device_on:
            k = max(drv.num_tree_per_iteration, 1)
            rows = min(self.max_batch_rows, self.chunk)
            self.scratch_bytes = rows * (self.num_feature * 4 + k * 4)
            # guarded upload (ISSUE 15): an allocation failure here is
            # classified and named instead of crashing the load as an
            # anonymous XlaRuntimeError — the registry retries after
            # eviction, then refuses with 507
            with membudget.oom_guard("registry_load", model=self.key):
                drv._packed_forest()  # pack + upload the tables once
                # what this model actually costs on device: the FULL
                # packed tables — PackedForest.device() uploads and
                # retains every tree regardless of the num_iteration a
                # request later slices to, so an early-stopped model's
                # resident bytes are the full pack (counting the slice
                # would undercount residency AND diverge from the
                # preflight plan, which prices the full host pack).
                # This is the capacity unit LRU eviction reports in
                # (bytes, not model count; ROADMAP 2c's quantized
                # tables shrink it)
                self.hbm_bytes = sum(
                    int(v.nbytes)
                    for v in drv._packed_forest().device().values())
        # the gauge is set by ModelRegistry.load's registration block,
        # not here: a load that fails after construction (warmup error)
        # must not leave a phantom per-model series
        # drift monitor (ISSUE 14): models carrying a
        # tpu_feature_profile: trailer get sampled input/score drift
        # tracking against their training profile.  The tap is one
        # bounded row copy per predict; binning + PSI/JS run at scrape
        # time (GET /drift, GET /metrics) — zero device programs, zero
        # work when the profile is absent or sampling is off
        self.drift = None
        sample_rows = int(config.serving_drift_sample_rows)
        profile = drv.health_profile()
        if profile is not None and sample_rows > 0 \
                and drv._pred_context() is not None:
            from ..obs.modelhealth import DriftMonitor

            ctx = drv._pred_context()
            self.drift = DriftMonitor(
                profile, ctx.mappers, sample_rows=sample_rows,
                psi_warn=float(config.serving_drift_psi_warn),
                model=self.key, stats=stats,
                num_feature=self.num_feature,
                # raw scores via the host walker: matches the profile's
                # raw-score histogram on every objective, and the
                # scrape path may not steal device time from dispatch
                score_fn=lambda Xs: drv.predict_raw(Xs, -1))
        # circuit breaker on the device path: threshold failures open it
        # (requests short-circuit to the native walker), a timed
        # half-open probe retries the device path
        self.breaker = CircuitBreaker(
            threshold=int(config.serving_breaker_failures),
            cooldown_s=float(config.serving_breaker_cooldown_ms) / 1e3,
            stats=stats)

    # ------------------------------------------------------------------
    @property
    def chunk(self) -> int:
        """The driver's LIVE predict chunk — read dynamically, never
        cached: an OOM-driven shrink (gbdt._shrink_predict_chunk) must
        flow into this entry's launch-bucket accounting immediately, or
        batch_fill_ratio / the shape series / the scratch reservation
        would report the pre-shrink launches forever."""
        return self.booster._driver.predict_chunk_rows()

    def default_num_iteration(self) -> int:
        """The num_iteration a None request resolves to — mirrors
        Booster.predict's best_iteration default, and is what warmup
        must pre-compile (an early-stopped model's sliced tree tables
        are a different jit shape than the full forest's)."""
        bi = self.booster.best_iteration
        return bi if bi is not None and bi >= 0 else -1

    def warm_signature(self):
        """Everything that keys this entry's predict programs: two
        entries with equal signatures trigger byte-identical jit cache
        keys for every warmup launch, so the registry runs the warmup
        sweep ONCE per signature — loading a second same-shaped model
        adds zero compiled programs AND zero warmup wall."""
        if not self.device_on:
            return None
        drv = self.booster._driver
        ni = self.default_num_iteration()
        total, _ = drv._model_subset(-1 if ni is None else ni)
        tables = drv._packed_forest().device(total)
        shapes = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                              for k, v in tables.items()))
        return (self.chunk, self.max_batch_rows, self.policy,
                self.num_feature, max(drv.num_tree_per_iteration, 1),
                _depth_bucket(drv._packed_forest().depth, self.policy),
                shapes)

    def warmup(self, precompiled: bool = False) -> int:
        """Pre-compile every launch shape; returns the bucket count.

        precompiled=True (another resident entry already warmed an equal
        `warm_signature`) skips the device launches and only registers
        the shapes with the stats accounting — the programs exist in the
        jit cache, so this entry's first real predicts are warm."""
        if not self.device_on:
            return 0
        buckets = predict_row_buckets(self.max_batch_rows, self.chunk,
                                      policy=self.policy)
        ni = self.default_num_iteration()
        for b in buckets:
            if precompiled:
                self.stats.note_shape((self.key, ni, b), warmup=True)
            else:
                self.predict(np.zeros((b, self.num_feature), np.float64),
                             num_iteration=ni, warmup=True)
        return len(buckets)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                num_iteration: int = -1, warmup: bool = False) -> np.ndarray:
        """The batch runner: one device predict with launch-shape
        accounting.  A device failure serves THIS batch via the native
        host walker and feeds the circuit breaker; past the failure
        threshold the breaker opens and requests short-circuit to the
        walker (zero device attempts) until a timed half-open probe
        finds the device path healthy again."""
        ni = -1 if num_iteration is None else int(num_iteration)
        if not warmup and self.drift is not None:
            # drift tap BEFORE any path split: input drift is a property
            # of the request, not of which predictor served it.  One
            # stride-sampled row copy + a GIL-atomic deque append — the
            # accumulation itself runs at scrape time, off this worker
            self.drift.tap(X)
        if not self.device_on:
            if not warmup:
                self.stats.note_batch(X.shape[0], X.shape[0])
            return self._native_predict(X, raw_score, ni)
        n = int(X.shape[0])
        bucket = row_bucket(n, self.chunk, policy=self.policy)
        if not warmup and not self.breaker.allow():
            # breaker open: no device launch happens, so account this
            # batch like the native path (unpadded rows)
            self.stats.note_batch(n, n)
            return self._native_predict(X, raw_score, ni)
        if not warmup:
            # a batch wider than the predict chunk runs ceil(n/chunk)
            # padded launches inside _chunked_device_scores — account
            # them all, or batch_fill_ratio would exceed 1.0
            launches = -(-n // self.chunk) if n > self.chunk else 1
            self.stats.note_batch(n, launches * bucket, launches=launches)
        self.stats.note_shape((self.key, ni, bucket), warmup=warmup)
        # generation snapshot: if the dispatch watchdog abandons this
        # call and records a failure while it runs, the success below
        # becomes stale and must not reset/close the breaker
        gen = self.breaker.generation
        # device walls are unbounded from the host's view: entering one
        # holding any serving/obs lock would stall every thread queued
        # on it (lockcheck flags it under tests)
        lockcheck.check_dispatch("registry.predict")
        try:
            if not warmup:
                action = faultline.fire("serve_dispatch", model=self.key)
                if action == "hang":
                    # simulate a wedged device stream: never return.
                    # The batcher's dispatch watchdog
                    # (serving_dispatch_timeout_ms) abandons this
                    # thread, fails the batch over to the native
                    # walker, and feeds the breaker
                    import time as _time

                    _time.sleep(3600.0)
            with membudget.oom_guard(
                    "registry_warmup" if warmup else "serve_dispatch",
                    model=self.key):
                out = self.booster.predict(X, raw_score=raw_score,
                                           num_iteration=ni,
                                           device="tpu",
                                           tpu_predict_device="true")
        except Exception as exc:
            # route through the membudget classifier FIRST: a dispatch
            # OOM is a pressure signal (count it, let the registry
            # evict cold models) before it is a device failure
            if membudget.is_oom_error(exc):
                if warmup:
                    # warmup must NOT silently walk a model that cannot
                    # fit: the load path (which owns its own eviction +
                    # retry + models_refused_hbm accounting — dispatch
                    # counters stay dispatch-only) retries or refuses
                    # with 507 instead of admitting a model whose every
                    # dispatch would OOM
                    raise
                self.stats.count("dispatch_oom")
                if self.pressure_cb is not None:
                    try:
                        self.pressure_cb(self.key)
                    except Exception:  # pragma: no cover - defensive
                        pass
            # count a fallback only when the host walker actually
            # serves it — a data error raises identically on both paths
            # and must not inflate the device-failure signal
            out = self._native_predict(X, raw_score, ni)
            self.stats.count("device_fallbacks")
            if not warmup:
                self.breaker.record_failure()
            return out
        if not warmup:
            self.breaker.record_success(gen)
        return out

    def _native_predict(self, X: np.ndarray, raw_score: bool,
                        ni: int) -> np.ndarray:
        return self.booster.predict(X, raw_score=raw_score,
                                    num_iteration=ni, device="cpu")

    # -- failover hooks (the batcher's on_error / fallback pair) -------
    @property
    def healthy(self) -> bool:
        """False while the device-path breaker is OPEN (requests are
        short-circuiting to the native walker)."""
        return self.breaker.state != "open"

    def native_runner(self, raw_score: bool, ni: int):
        """The failover target: a pure host-walker runner for this
        entry — the 'healthy replica' of last resort.  The batcher
        re-runs a batch on it when the device dispatch raises or hangs,
        so riders get answers instead of the failure.  (Mesh replicas
        slot into this hook: a multi-device registry returns another
        device's runner here before degrading to the walker.)"""
        def run(Xb: np.ndarray) -> np.ndarray:
            return self._native_predict(Xb, raw_score, ni)
        return run

    def record_dispatch_error(self, exc: BaseException) -> bool:
        """Classify a dispatch failure for the batcher: True = device-
        path failure (feed the breaker, fail the batch over to the
        native runner); False = caller error (malformed rows raise
        identically on both paths — failing over would mask a 400 as a
        fallback and poison the breaker signal)."""
        from ..utils.log import LightGBMError

        if isinstance(exc, (LightGBMError, ValueError, KeyError,
                            TypeError)):
            return False
        # device/XLA error or a hang promoted to ServingTimeout by the
        # dispatch watchdog: the breaker keeps later requests off the
        # device path until a half-open probe finds it healthy
        self.breaker.record_failure()
        return True

    def describe(self) -> Dict:
        return {"key": self.key, "name": self.name, "version": self.version,
                "num_feature": self.num_feature,
                "num_trees": self.booster.num_trees(),
                "device": bool(self.device_on),
                "hbm_bytes": int(self.hbm_bytes),
                "breaker": self.breaker.state,
                "healthy": self.healthy,
                "drift_monitor": self.drift is not None}


class ModelRegistry:
    """name@version -> ModelEntry with LRU eviction and hot-swap."""

    def __init__(self, config: Optional[Config] = None,
                 stats: Optional[ServingStats] = None):
        self.config = config if config is not None else Config({})
        self.stats = stats if stats is not None else ServingStats()
        self._lock = lockcheck.make_rlock("serving.registry")
        self._entries: "OrderedDict[str, ModelEntry]" = OrderedDict()
        self._latest: Dict[str, str] = {}   # name -> current key
        self._counts: Dict[str, int] = {}   # name -> loads so far
        # warm signatures already compiled in this process: a second
        # same-shaped model load skips the warmup device launches
        self._warmed: set = set()

    # ------------------------------------------------------------------
    def load(self, name: str, model_file: Optional[str] = None,
             model_str: Optional[str] = None, booster=None,
             params: Optional[Dict] = None,
             version: Optional[str] = None) -> ModelEntry:
        """Load + warm a model, then atomically flip `name` to it.

        The expensive part (parse, pack, warmup compiles) runs OUTSIDE
        the registry lock: a hot-swap never blocks serving of the old
        version.  A user-supplied `booster` is adopted as-is (its
        tpu_predict_device param may be promoted to 'true')."""
        if "@" in name:
            raise ValueError("model name must not contain '@' "
                             "(reserved for name@version keys)")
        if booster is None:
            from ..booster import Booster

            merged = dict(params or {})
            if model_file is not None:
                booster = Booster(params=merged, model_file=model_file)
            elif model_str is not None:
                booster = Booster(params=merged, model_str=model_str)
            else:
                raise ValueError(
                    "load needs model_file=, model_str= or booster=")
        with self._lock:
            if version is not None:
                ver = str(version)
                # keep the implicit counter ahead of explicit NUMERIC
                # versions so a later version-less load never reuses (and
                # silently replaces) an existing name@N entry
                try:
                    self._counts[name] = max(self._counts.get(name, 0),
                                             int(ver))
                except ValueError:
                    pass
            else:
                self._counts[name] = self._counts.get(name, 0) + 1
                ver = str(self._counts[name])
        # HBM budget preflight (ISSUE 15): predicted packed-table +
        # launch-scratch bytes BEFORE any upload.  Over budget -> evict
        # cold models to make room; still over -> structured 507
        # refusal instead of warming into a device crash
        self._preflight_load(name, ver, booster)
        entry = self._build_entry(name, ver, booster)
        entry.pressure_cb = self._on_dispatch_oom
        with self._lock:
            # the AUTHORITATIVE budget wall, re-checked under the lock:
            # the pre-upload preflight read resident bytes without it,
            # so two concurrent over-half-budget loads could both pass
            # and jointly breach the wall — admission is serialized
            # here, where insertion is
            budget = self._budget()
            if budget is not None and entry.hbm_bytes:
                def over():
                    return self._admission_overflow_locked(
                        entry.key, entry.hbm_bytes,
                        entry.scratch_bytes, budget) > 0
                if over():
                    self._evict_cold_locked(lambda _f, _n: not over())
                if over():
                    self.stats.count("models_refused_hbm")
                    raise membudget.ServingMemoryExhausted(
                        f"loading model {entry.key} would put "
                        f"{self._resident_bytes_locked() + entry.hbm_bytes:,d} "
                        "resident device bytes (plus launch scratch) "
                        f"against the {budget:,d}-byte serving HBM "
                        "budget (a concurrent load took the "
                        "headroom); retry or raise the budget",
                        site="registry_load", info={"model": name})
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            self.stats.set_model_hbm(entry.key, entry.hbm_bytes)
            # a reloaded key re-arms drift publishing (clear_drift
            # tombstones it on unload/eviction so an in-flight scrape
            # cannot resurrect a departed model's gauges)
            self.stats.reopen_drift(entry.key)
            # atomic flip (hot-swap) — but never BACKWARDS: concurrent
            # loads finish warmup in arbitrary order, and last-finisher-
            # wins would let a stale version steal the alias
            if not self._version_newer(self._latest.get(name), ver):
                self._latest[name] = entry.key
            self.stats.count("models_loaded")
            self._evict_locked()
        return entry

    # -- memory pressure (ISSUE 15) ------------------------------------
    def _budget(self) -> Optional[int]:
        return membudget.serving_budget_bytes(self.config)

    def _resident_bytes_locked(self) -> int:
        return sum(e.hbm_bytes for e in self._entries.values())

    def _admission_overflow_locked(self, key: str, new_tables: int,
                                   new_scratch: int, budget: int) -> int:
        """THE serving admission formula — bytes over budget (<= 0
        fits), shared by the pre-upload preflight and the under-lock
        registration wall so the two can never drift apart (a mismatch
        would let an uncontended load pass preflight, burn the upload +
        warmup, then be refused at the wall): resident tables — minus a
        same-`key` entry about to be replaced IN PLACE, whose bytes
        leave as the new ones land — plus the new tables, plus the MAX
        launch scratch across entries (dispatches serialize, so scratch
        reserves once)."""
        resident = sum(e.hbm_bytes for e in self._entries.values()
                       if e.key != key)
        scratch = max([e.scratch_bytes for e in self._entries.values()
                       if e.key != key] + [new_scratch])
        return resident + new_tables + scratch - budget

    def _preflight_load(self, name: str, ver: str, booster) -> None:
        """Refuse (507) a load whose PREDICTED device bytes cannot fit
        the serving budget, evicting cold models first — the planner
        runs off the host pack, so nothing touches HBM before the
        verdict.  Applies `_admission_overflow_locked`, the SAME
        formula the under-lock wall re-checks at registration."""
        budget = self._budget()
        if budget is None:
            return
        membudget.publish_budget_gauge(budget, "serving")
        plan = membudget.plan_model_load(booster, self.config)
        if plan is None:
            return  # no device path: nothing lands in HBM
        tables = plan.components.get("packed_tables", 0)
        if tables > 0:
            key = f"{name}@{ver}"
            new_scratch = plan.components.get("launch_scratch", 0)
            with self._lock:
                overflow = self._admission_overflow_locked(
                    key, tables, new_scratch, budget)
            if overflow > 0:
                self.relieve_pressure(need_bytes=overflow)
                with self._lock:
                    overflow = self._admission_overflow_locked(
                        key, tables, new_scratch, budget)
            if overflow > 0:
                self.stats.count("models_refused_hbm")
                from ..obs import flightrecorder

                with self._lock:
                    resident = self._resident_bytes_locked()
                flightrecorder.note("oom", "load_refused", model=name,
                                    predicted=plan.total,
                                    resident=resident, budget=budget)
                raise membudget.ServingMemoryExhausted(
                    plan.refuse_message(
                        f"loading model {name!r} "
                        f"({resident:,d} bytes already resident)"),
                    site="registry_load",
                    info={"model": name, "resident_bytes": resident})

    def _build_entry(self, name: str, ver: str, booster) -> ModelEntry:
        """Construct + warm the entry; a classified OOM during the
        upload or warmup evicts cold models and retries ONCE, then
        refuses with the structured 507 — an under-budget prediction
        that still OOMs (fragmentation, co-tenants) must not crash the
        process or silently admit a walker-only model."""
        for attempt in (0, 1):
            try:
                entry = ModelEntry(name, ver, booster, self.config,
                                   self.stats)
                if bool(self.config.serving_warmup):
                    # dedupe warmup compiles across models sharing a
                    # launch-shape signature: the jit cache is process-
                    # wide, so a second same-shaped model's sweep would
                    # only re-execute programs that already exist
                    sig = entry.warm_signature()
                    with self._lock:
                        seen = sig is not None and sig in self._warmed
                    entry.warmup(precompiled=seen)
                    # marked warmed only AFTER the sweep succeeds: a
                    # failed (or concurrent, still-compiling) warmup
                    # must not make future same-shaped loads skip
                    # theirs and serve cold compiles
                    if sig is not None:
                        with self._lock:
                            self._warmed.add(sig)
                return entry
            except membudget.DeviceOutOfMemory as exc:
                freed = self.relieve_pressure()
                if attempt == 1 or not freed:
                    self.stats.count("models_refused_hbm")
                    raise membudget.ServingMemoryExhausted(
                        f"loading model {name!r} ran out of device "
                        f"memory at {exc.site!r} and eviction could "
                        "not free enough; refuse instead of serving a "
                        "model whose every dispatch would OOM",
                        site=exc.site, info=dict(exc.info)) from exc
                Log.warning(
                    f"device OOM at {exc.site!r} while loading "
                    f"{name!r}: evicted {freed} cold device bytes, "
                    "retrying the load once")

    def _on_dispatch_oom(self, key: str) -> None:
        """A dispatch-path OOM reported by an entry: sustained pressure
        — evict a cold model so the NEXT dispatch has headroom (the
        failing batch itself was already served by the walker)."""
        freed = self.relieve_pressure()
        if freed:
            Log.warning(f"dispatch OOM on {key}: evicted {freed} cold "
                        "device bytes under memory pressure")

    def relieve_pressure(self, need_bytes: int = 0) -> int:
        """Evict cold (non-current) LRU models until `need_bytes` are
        freed (0 = exactly one victim); returns the bytes actually
        freed.  Current aliases are never evicted here — shedding the
        model a caller is actively resolving trades one failure for
        another."""
        with self._lock:
            if need_bytes > 0:
                done = lambda freed, n: freed >= need_bytes  # noqa: E731
            else:
                done = lambda freed, n: n >= 1               # noqa: E731
            freed = self._evict_cold_locked(done)
            self._publish_pressure_locked()
        return freed

    def _evict_cold_locked(self, done) -> int:
        """Evict cold (non-current) DEVICE-BACKED LRU entries until
        `done(freed_bytes, victims)` or none remain — the ONE eviction
        body every pressure path shares (the per-victim bookkeeping
        must never skew between them).  Zero-byte (walker-only) entries
        are never pressure victims: evicting them frees no HBM, and a
        byte-driven sweep would otherwise clear every one of them for
        nothing (the serving_max_models count cap owns their slots)."""
        freed = 0
        n = 0
        current = set(self._latest.values())
        while not done(freed, n):
            victim = next((k for k, e in self._entries.items()
                           if k not in current and e.hbm_bytes > 0),
                          None)
            if victim is None:
                break
            got = int(self._entries[victim].hbm_bytes)
            freed += got
            n += 1
            del self._entries[victim]
            self.stats.count("models_evicted")
            self.stats.count("evictions_pressure")
            self.stats.clear_model_hbm(victim)
            self.stats.clear_drift(victim)
            Log.info(f"serving registry evicted {victim} under memory "
                     f"pressure: freed {got} device bytes")
        return freed

    def _publish_pressure_locked(self) -> None:
        total = self._resident_bytes_locked()
        self.stats.set_total_hbm(total)
        budget = self._budget()
        if budget:
            self.stats.set_hbm_pressure(total / budget)

    @staticmethod
    def _version_newer(current_key: Optional[str], candidate: str) -> bool:
        """True when the currently-aliased version outranks `candidate`
        (numeric compare when both versions are numeric, else the flip
        always proceeds — explicit string versions are caller-ordered)."""
        if current_key is None:
            return False
        try:
            return int(current_key.rsplit("@", 1)[1]) > int(candidate)
        except (ValueError, IndexError):
            return False

    def _evict_locked(self) -> None:
        cap = max(int(self.config.serving_max_models), 1)
        while len(self._entries) > cap:
            current = set(self._latest.values())
            victim = next((k for k in self._entries if k not in current),
                          None)
            if victim is None:
                # every entry is someone's current version: retire the
                # least-recently-used name entirely
                victim = next(iter(self._entries))
                self._latest = {n: k for n, k in self._latest.items()
                                if k != victim}
            freed = int(self._entries[victim].hbm_bytes)
            del self._entries[victim]
            self.stats.count("models_evicted")
            self.stats.clear_model_hbm(victim)
            self.stats.clear_drift(victim)
            Log.info(f"serving registry evicted {victim}: freed {freed} "
                     "device bytes "
                     f"({len(self._entries)}/{cap} models resident)")
        # sustained byte pressure (ISSUE 15): past the pressure
        # fraction of the serving HBM budget, cold (non-current) LRU
        # models leave ahead of demand — before a dispatch has to OOM
        budget = self._budget()
        if budget:
            frac = float(self.config.serving_hbm_pressure_frac)
            threshold = int(budget * max(min(frac, 1.0), 0.05))
            self._evict_cold_locked(
                lambda _f, _n: self._resident_bytes_locked() <= threshold)
        self._publish_pressure_locked()

    # ------------------------------------------------------------------
    def admission_headroom(self, new_tables: int,
                           new_scratch: int = 0) -> Optional[int]:
        """Serving-budget bytes left for a NEW entry of the given size
        (negative = would not fit; None = no budget configured, always
        admissible).  The continual controller preflights a candidate
        retrain against this BEFORE spending the training wall: the
        two-generation swap needs candidate+live resident together, so
        a candidate that cannot be admitted defers the retrain instead
        of OOM-crashing the shadow load."""
        budget = self._budget()
        if budget is None:
            return None
        with self._lock:
            return -self._admission_overflow_locked(
                "", int(new_tables), int(new_scratch), budget)

    def promote(self, name: str, key: str) -> Optional[str]:
        """Atomically re-alias bare `name` to an ALREADY-RESIDENT entry
        (shadow-gated promotion, ISSUE 17): the candidate was loaded,
        warmed and scored under a shadow name; the flip here is one
        dict store under the registry lock — in-flight requests that
        already resolved the old entry finish against it, new resolves
        see the promoted one.  Zero requests are dropped or double-
        answered because nothing else changes.  Returns the previously
        aliased key (None when `name` had no alias) so the caller can
        roll back with another `promote(name, prev_key)`."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                raise KeyError(
                    f"no resident entry {key!r} to promote as {name!r}")
            prev = self._latest.get(name)
            self._latest[name] = key
            self._entries.move_to_end(key)  # LRU touch: now current
            return prev

    def resolve(self, name: str) -> ModelEntry:
        """`name` (current version) or exact `name@version` -> entry."""
        with self._lock:
            key = self._latest.get(name, name)
            entry = self._entries.get(key)
            if entry is None:
                raise KeyError(f"no model {name!r} in the serving registry")
            self._entries.move_to_end(key)  # LRU touch
            return entry

    def unload(self, name: str) -> None:
        """Drop one version (`name@version`) or, for a bare name, EVERY
        resident version of it — an operator unload must actually
        release the packed device tables, not just the current alias.
        Unloading the CURRENT version re-aliases the name to its newest
        surviving version (the rollback workflow), rather than leaving
        resident versions unreachable by bare name."""
        with self._lock:
            if "@" in name:
                victims = [name]
            else:
                victims = [k for k, e in self._entries.items()
                           if e.name == name]
                alias = self._latest.get(name)
                if alias is not None and alias not in victims:
                    # a cross-name promotion (a shadow entry aliased
                    # under this name) must leave with the name it
                    # serves, not survive as an unreachable resident
                    victims.append(alias)
            removed = [self._entries.pop(k) for k in victims
                       if k in self._entries]
            for e in removed:
                self.stats.clear_model_hbm(e.key)
                self.stats.clear_drift(e.key)
                if e.hbm_bytes:
                    Log.info(f"serving registry unloaded {e.key}: freed "
                             f"{int(e.hbm_bytes)} device bytes")
            self._publish_pressure_locked()
            gone = set(victims)
            self._latest = {n: k for n, k in self._latest.items()
                            if k not in gone and n != name}
            for e in removed:
                if e.name in self._latest:
                    continue
                survivors = [k for k, s in self._entries.items()
                             if s.name == e.name]
                if survivors:
                    self._latest[e.name] = max(
                        survivors, key=self._version_rank)

    @staticmethod
    def _version_rank(key: str):
        ver = key.rsplit("@", 1)[1]
        try:
            return (1, int(ver), ver)
        except ValueError:
            return (0, 0, ver)

    def models(self) -> List[Dict]:
        with self._lock:
            current = {k: n for n, k in self._latest.items()}
            return [{**e.describe(), "current": e.key in current}
                    for e in self._entries.values()]

    def entries(self) -> List[ModelEntry]:
        """Resident entries, snapshot under the lock (no LRU touch) —
        the drift scrape iterates this without blocking loads."""
        with self._lock:
            return list(self._entries.values())
