"""Micro-batching queue: coalesce concurrent predicts into one launch.

Sustained accelerator throughput comes from the batching layer above the
kernel, not the kernel itself (arXiv:1806.11248, arXiv:2005.09148): a
stream of small independent predict requests must ride a handful of
fixed launch shapes instead of paying one dispatch (or worse, one
compile) each.  The batcher:

* queues requests per **batch key** — (model, predict options) — so only
  result-compatible requests ever share a launch,
* holds an under-filled batch open up to `max_wait_ms`, dispatching
  early once `max_batch_rows` rows have coalesced,
* runs batches on ONE worker thread (device access is serialized; jit
  caches and packed-forest tables never see concurrent mutation),
* scatters each request's row slice back and wakes its caller,
* sheds load at admission time: past `queue_rows` queued rows new
  requests fail immediately with `ServingQueueFull` instead of growing
  an unbounded backlog.

Row-bucket padding itself happens in the ops layer
(`ops.predict.row_bucket` via `gbdt._chunked_device_scores`) — the
batcher only bounds *batch composition*; the registry entry accounts the
resulting launch shape against the compile cache.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Hashable, Optional

import numpy as np

from .stats import ServingStats


class ServingQueueFull(RuntimeError):
    """Admission control rejected the request (queue at capacity)."""

    http_status = 503


class ServingTimeout(TimeoutError):
    """The request waited past its serving_timeout_ms budget."""

    http_status = 504


class _Request:
    __slots__ = ("X", "n", "done", "result", "error", "t_submit",
                 "abandoned")

    def __init__(self, X: np.ndarray):
        self.X = X
        self.n = int(X.shape[0])
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.abandoned = False  # caller timed out; skip, don't compute


class MicroBatcher:
    """Bounded coalescing queue + single dispatch worker."""

    def __init__(self, max_batch_rows: int = 4096, max_wait_ms: float = 2.0,
                 queue_rows: int = 65536,
                 stats: Optional[ServingStats] = None):
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.queue_rows = max(int(queue_rows), 1)
        self.stats = stats if stats is not None else ServingStats()
        self._cv = threading.Condition()
        self._queues: "OrderedDict[Hashable, deque]" = OrderedDict()
        self._runners: dict = {}
        self._pending_rows = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "MicroBatcher":
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serving-batcher",
                    daemon=True)
                self._thread.start()
        return self

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------------
    def submit(self, key: Hashable, runner: Callable[[np.ndarray], np.ndarray],
               X: np.ndarray) -> _Request:
        """Enqueue one request; returns a handle for `wait`.

        `runner(X_batch)` must be row-independent: request i's rows in a
        coalesced batch produce the same values they would alone (the
        bin-space traversal is, per construction)."""
        return self.submit_many(key, runner, [X])[0]

    def submit_many(self, key: Hashable,
                    runner: Callable[[np.ndarray], np.ndarray],
                    slices) -> list:
        """Enqueue the slices of ONE logical request atomically:
        admission is all-or-nothing (a mid-request shed would leave
        already-queued slices burning device time for a caller that
        already got ServingQueueFull), and the counters see one request."""
        reqs = [_Request(X) for X in slices]
        if not reqs:
            # an empty deque would crash the dispatch worker's oldest-
            # head selection and brick the whole session
            raise ValueError("submit_many needs at least one slice")
        total = sum(r.n for r in reqs)
        with self._cv:
            if self._stop:
                raise RuntimeError("batcher is closed")
            if self._pending_rows + total > self.queue_rows:
                self.stats.count("requests_shed")
                raise ServingQueueFull(
                    f"serving queue full: {self._pending_rows} rows queued, "
                    f"request of {total} exceeds serving_queue_rows="
                    f"{self.queue_rows}")
            self.stats.count("requests_total")
            self.stats.count("rows_total", total)
            if key not in self._queues:
                self._queues[key] = deque()
            self._queues[key].extend(reqs)
            self._runners[key] = runner
            self._pending_rows += total
            self.stats.set_queue_depth(self._pending_rows)
            self._cv.notify_all()
        return reqs

    def wait(self, req: _Request, timeout_s: float) -> np.ndarray:
        if not req.done.wait(timeout_s):
            # the caller is gone: mark the queued slices so the worker
            # sheds them instead of burning device time on a result
            # nobody will read (goodput under overload)
            req.abandoned = True
            self.stats.count("requests_timeout")
            raise ServingTimeout(
                f"request of {req.n} rows not served within "
                f"{timeout_s * 1e3:.0f} ms")
        if req.error is not None:
            # failed requests stay out of the latency window: fast-
            # failing error streams would otherwise drag p50/p99 down
            # exactly while the service is erroring
            raise req.error
        self.stats.record_latency(time.monotonic() - req.t_submit)
        return req.result

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._queues:
                    self._cv.wait()
                if self._stop and not self._queues:
                    return
                # serve the key whose head request has waited longest
                key = min(self._queues,
                          key=lambda k: self._queues[k][0].t_submit)
                dq = self._queues[key]
                rows = sum(r.n for r in dq)
                deadline = dq[0].t_submit + self.max_wait_s
                now = time.monotonic()
                if rows < self.max_batch_rows and now < deadline \
                        and not self._stop:
                    # hold the batch open for more coalescing
                    self._cv.wait(deadline - now)
                    continue
                batch = []
                take = 0
                dropped = 0
                t_pop = time.monotonic()
                while dq and (not batch
                              or take + dq[0].n <= self.max_batch_rows):
                    r = dq.popleft()
                    if r.abandoned:
                        dropped += r.n
                        r.done.set()
                        continue
                    # queue wait = submit -> dispatch start: the number
                    # that separates "the device is slow" from "the
                    # queue is deep" when p99 climbs
                    self.stats.record_queue_wait(t_pop - r.t_submit)
                    batch.append(r)
                    take += r.n
                runner = self._runners[key]
                if not dq:
                    # drop the drained queue AND its runner: a stale
                    # runner closure would pin its ModelEntry (packed
                    # device forest included) long past LRU eviction
                    del self._queues[key]
                    del self._runners[key]
                self._pending_rows -= take + dropped
                self.stats.set_queue_depth(self._pending_rows)
            if batch:
                self._run(runner, batch)

    def _run(self, runner, batch) -> None:
        from .. import obs

        X = batch[0].X if len(batch) == 1 else \
            np.concatenate([r.X for r in batch], axis=0)
        t0 = time.monotonic()
        try:
            with obs.span("serve/dispatch", rows=int(X.shape[0])):
                out = runner(X)
        except BaseException as exc:  # delivered to every waiter
            for r in batch:
                r.error = exc
                r.done.set()
            return
        finally:
            self.stats.record_dispatch(time.monotonic() - t0)
        off = 0
        for r in batch:
            # axis-0 slice works for [n] and [n, k] outputs alike; padded
            # launch rows were already cut off inside the ops layer
            r.result = out[off:off + r.n]
            off += r.n
            r.done.set()
