"""Micro-batching queue: coalesce concurrent predicts into one launch.

Sustained accelerator throughput comes from the batching layer above the
kernel, not the kernel itself (arXiv:1806.11248, arXiv:2005.09148): a
stream of small independent predict requests must ride a handful of
fixed launch shapes instead of paying one dispatch (or worse, one
compile) each.  The batcher:

* queues requests per **batch key** — (model, predict options) — so only
  result-compatible requests ever share a launch,
* holds an under-filled batch open up to the ADAPTIVE coalescing window
  (`window_fn`, the admission controller's SLO-coupled value between
  `serving_min_wait_ms` and `serving_max_wait_ms`; static
  `serving_max_wait_ms` without a controller), dispatching early once
  `max_batch_rows` rows have coalesced,
* runs batches on one dispatch worker PER SERVING DEVICE (ISSUE 19):
  a replicated model's batches route to the least-loaded worker
  (queued rows + in-flight rows) whose device the entry reports
  routable, so a wedged or OOMing device routes around, not down;
  non-replicated runners pin to worker 0, which preserves the original
  serialized-dispatch semantics (each worker serializes ITS device's
  access; jit caches and packed-forest tables never see concurrent
  mutation because replicas are per-device objects),
* scatters each request's row slice back and wakes its caller,
* sheds load at admission time: past `queue_rows` queued rows new
  requests fail immediately with `ServingQueueFull` instead of growing
  an unbounded backlog,
* **cancels expired requests in queue**: a request whose propagated
  deadline (`X-Deadline-Ms`) passes while it waits is answered with
  `ServingExpired` at pop time and never reaches the device — under
  overload, device seconds go to requests that can still make their
  budget (counted `requests_expired`, separate from the
  `requests_timeout` dispatch-wait expiries),
* **fails over a dying dispatch**: a runner that raises — or hangs past
  `dispatch_timeout_s` — reports to `on_error` (the registry's health
  hook feeding the per-entry CircuitBreaker) and the batch re-runs on
  the `fallback` runner (the native host walker) instead of failing
  every rider.  (The registry's own runner already absorbs plain
  raises internally — `ModelEntry.predict` serves the batch via the
  walker and feeds the breaker itself — so for that runner this layer
  is the HANG backstop plus a second line for anything that escapes;
  for raw runners it is the only one.)  An abandoned dispatch keeps
  running on the serial helper thread, which refuses new device work
  until it finishes — device calls never overlap,
* **drains**: `drain()` closes admission (`RuntimeError` on submit;
  the session maps it to 503 + Retry-After upstream), flushes every
  queued batch, and `close()` joins the worker — zero requests lost,
  none answered twice (each `_Request.done` fires exactly once).

Row-bucket padding itself happens in the ops layer
(`ops.predict.row_bucket` via `gbdt._chunked_device_scores`) — the
batcher only bounds *batch composition*; the registry entry accounts the
resulting launch shape against the compile cache.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Hashable, Optional

import numpy as np

from ..utils import lockcheck
from .stats import ServingStats


class ServingQueueFull(RuntimeError):
    """Admission control rejected the request (queue at capacity)."""

    http_status = 503


class ServingTimeout(TimeoutError):
    """The request waited past its serving_timeout_ms budget."""

    http_status = 504


class ServingExpired(ServingTimeout):
    """The request's propagated deadline passed while it sat in queue;
    it was cancelled before burning device time.  Subclasses
    ServingTimeout (same 504 surface) but counts separately
    (`requests_expired` vs `requests_timeout`)."""

    http_status = 504


class _Request:
    __slots__ = ("X", "n", "done", "result", "error", "t_submit",
                 "abandoned", "deadline", "group")

    def __init__(self, X: np.ndarray, deadline: Optional[float] = None,
                 group: Optional[dict] = None):
        self.X = X
        self.n = int(X.shape[0])
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.abandoned = False  # caller timed out; skip, don't compute
        self.deadline = deadline  # absolute monotonic expiry (or None)
        # shared across the slices of one LOGICAL request, so per-
        # request counters (requests_expired) count once however many
        # slices carry the deadline
        self.group = group if group is not None else {}


class _KeyState:
    """Per-batch-key dispatch plumbing: the runner plus its failover.

    `per_device` runners accept a `device=` kwarg (the worker index the
    batch landed on); `device_ok(index)` is the registry's NON-consuming
    routability filter (per-replica breaker peek) the router applies
    before load scoring."""

    __slots__ = ("runner", "fallback", "on_error", "per_device",
                 "device_ok")

    def __init__(self, runner, fallback=None, on_error=None,
                 per_device=False, device_ok=None):
        self.runner = runner
        self.fallback = fallback
        self.on_error = on_error
        self.per_device = bool(per_device)
        self.device_ok = device_ok


class _SerialDispatcher:
    """ONE long-lived helper thread that runs device dispatches for the
    watchdog path.  Serialization is the point: a dispatch the watchdog
    abandoned (slow or wedged) keeps running here, and `try_submit`
    refuses new device work until it finishes — so two device calls can
    never overlap (the jit caches / packed tables single-writer
    invariant survives abandonment), and the refused batches fail over
    to the host walker instead.  A long-lived thread also keeps
    thread-spawn churn off the per-batch hot path."""

    def __init__(self):
        self._lock = lockcheck.make_lock("serving.dispatcher")
        self._work = None
        self._have = threading.Event()
        self._busy = False
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while True:
            self._have.wait()
            with self._lock:
                work, self._work = self._work, None
                self._have.clear()
            if work is None:
                continue
            runner, X, box, done = work
            try:
                box["out"] = runner(X)
            except BaseException as exc:  # delivered to the waiter
                box["exc"] = exc
            finally:
                done.set()
                with self._lock:
                    self._busy = False

    def try_submit(self, runner, X):
        """(done_event, box), or None while the previous (abandoned)
        dispatch is still running — the caller fails over."""
        with self._lock:
            if self._busy:
                return None
            self._busy = True
            box: dict = {}
            done = threading.Event()
            self._work = (runner, X, box, done)
            self._have.set()
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serving-dispatch",
                    daemon=True)
                self._thread.start()
        return done, box


class _DeviceWorker:
    """One device's dispatch lane: a bounded hand-off queue, a thread
    that runs batches strictly one at a time, and its own serial
    watchdog helper (an abandoned dispatch wedges THIS device's lane;
    siblings keep serving).  Per-device goodput accounting feeds
    `MicroBatcher.device_snapshot()` (the `serve_bench --devices`
    breakdown) without touching the shared stats lock on the hot path.

    Lock order: `MicroBatcher._cv` and `_DeviceWorker._cv` are never
    held together — the router reads `load()` and calls `put()` after
    releasing the batcher lock, and `_run`'s completion accounting
    (`_batch_done`) takes only the batcher lock."""

    _LAT_RING = 512  # bounded per-device batch-wall samples (p99 window)

    def __init__(self, batcher: "MicroBatcher", index: int):
        self.batcher = batcher
        self.index = int(index)
        self._cv = threading.Condition(
            lockcheck.make_lock(f"serving.worker{index}"))
        self._work: deque = deque()
        self._queued_rows = 0
        self._inflight_rows = 0
        self._stop = False
        self._dispatches = 0
        self._rows_done = 0
        self._wall_s = 0.0
        self._lat: deque = deque(maxlen=self._LAT_RING)
        self.dispatcher = _SerialDispatcher()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop,
                    name=f"lgbm-serving-worker{self.index}", daemon=True)
                self._thread.start()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def load(self) -> int:
        """Routing score: rows queued on + in flight through this lane."""
        with self._cv:
            return self._queued_rows + self._inflight_rows

    def put(self, ks: _KeyState, batch, rows: int) -> None:
        with self._cv:
            self._work.append((ks, batch, int(rows)))
            self._queued_rows += int(rows)
            self._cv.notify_all()

    def note(self, rows: int, wall_s: float) -> None:
        with self._cv:
            self._dispatches += 1
            self._rows_done += int(rows)
            self._wall_s += float(wall_s)
            self._lat.append(float(wall_s))

    def snapshot(self) -> dict:
        with self._cv:
            lat = sorted(self._lat)
            p99 = lat[min(int(0.99 * (len(lat) - 1) + 0.5),
                          len(lat) - 1)] if lat else 0.0
            return {"device": self.index,
                    "dispatches": self._dispatches,
                    "rows": self._rows_done,
                    "wall_s": round(self._wall_s, 6),
                    "dispatch_p99_ms": round(p99 * 1e3, 3),
                    "queued_rows": self._queued_rows,
                    "inflight_rows": self._inflight_rows}

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._work and not self._stop:
                    self._cv.wait()
                if not self._work:
                    return  # stopping with an empty lane: nothing lost
                ks, batch, rows = self._work.popleft()
                self._queued_rows -= rows
                self._inflight_rows += rows
            try:
                # _run completes the batch end-to-end (dispatch,
                # failover, scatter, _batch_done accounting)
                self.batcher._run(ks, batch, device=self.index,
                                  worker=self)
            finally:
                with self._cv:
                    self._inflight_rows -= rows


class MicroBatcher:
    """Bounded coalescing queue + one dispatch worker per device."""

    def __init__(self, max_batch_rows: int = 4096, max_wait_ms: float = 2.0,
                 queue_rows: int = 65536,
                 stats: Optional[ServingStats] = None,
                 window_fn: Optional[Callable[[], float]] = None,
                 dispatch_timeout_ms: float = 0.0,
                 devices: int = 1):
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.queue_rows = max(int(queue_rows), 1)
        self.stats = stats if stats is not None else ServingStats()
        # adaptive coalescing window: consulted per batch; None = static
        self.window_fn = window_fn
        self.dispatch_timeout_s = max(float(dispatch_timeout_ms), 0.0) / 1e3
        self._cv = threading.Condition()
        self._workers = [_DeviceWorker(self, i)
                         for i in range(max(int(devices), 1))]
        self._queues: "OrderedDict[Hashable, deque]" = OrderedDict()
        self._runners: "dict[Hashable, _KeyState]" = {}
        # rows IN THE SYSTEM: queued here, handed to a worker lane, or
        # in flight on a device.  Decremented when the batch COMPLETES
        # (`_batch_done`), not at pop — `queue_rows` stays a true bound
        # on admitted-but-unfinished work, and the admission gate sees
        # the real backlog across every lane.  (Expired/abandoned rows
        # leave at pop; they never reach a lane.)
        self._pending_rows = 0
        self._stop = False
        self._draining = False
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def devices(self) -> int:
        return len(self._workers)

    def device_snapshot(self) -> list:
        """Per-device dispatch accounting (the `serve_bench --devices`
        breakdown): dispatches, rows, wall, p99, live lane depth."""
        return [w.snapshot() for w in self._workers]

    # ------------------------------------------------------------------
    def start(self) -> "MicroBatcher":
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._draining = False
                self._drained.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serving-batcher",
                    daemon=True)
                self._thread.start()
        for w in self._workers:
            w.start()
        return self

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Close admission and flush: new submits raise, the worker
        dispatches every queued batch, then parks.  Returns True when
        the flush completed inside `timeout_s` (False = still flushing;
        nothing is lost either way, the worker keeps going).  Safe to
        call twice; `close()` implies it."""
        with self._cv:
            self._draining = True
            if not self._queues and self._pending_rows == 0 \
                    and (self._thread is None
                         or not self._thread.is_alive()):
                self._drained.set()
            self._cv.notify_all()
        if self._thread is None or not self._thread.is_alive():
            # no worker: queued requests can never flush; report state
            with self._cv:
                return not self._queues and self._pending_rows == 0
        return self._drained.wait(timeout_s)

    @property
    def draining(self) -> bool:
        return self._draining

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._draining = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # workers flush their lanes before exiting (zero requests lost)
        for w in self._workers:
            w.close()

    # ------------------------------------------------------------------
    def submit(self, key: Hashable, runner: Callable[[np.ndarray], np.ndarray],
               X: np.ndarray, **kw) -> _Request:
        """Enqueue one request; returns a handle for `wait`.

        `runner(X_batch)` must be row-independent: request i's rows in a
        coalesced batch produce the same values they would alone (the
        bin-space traversal is, per construction)."""
        return self.submit_many(key, runner, [X], **kw)[0]

    def submit_many(self, key: Hashable,
                    runner: Callable[[np.ndarray], np.ndarray],
                    slices, deadline: Optional[float] = None,
                    fallback: Optional[Callable] = None,
                    on_error: Optional[Callable] = None,
                    per_device: bool = False,
                    device_ok: Optional[Callable] = None) -> list:
        """Enqueue the slices of ONE logical request atomically:
        admission is all-or-nothing (a mid-request shed would leave
        already-queued slices burning device time for a caller that
        already got ServingQueueFull), and the counters see one request.

        deadline: absolute monotonic expiry propagated from the caller
        (X-Deadline-Ms); slices still queued past it are cancelled at
        pop time instead of dispatched.  fallback/on_error: the
        device-failover hooks (see module docstring).  per_device: the
        runner accepts `device=` and batches may route to any worker;
        device_ok(index): non-consuming routability filter applied
        before least-loaded selection."""
        group: dict = {}
        reqs = [_Request(X, deadline, group) for X in slices]
        if not reqs:
            # an empty deque would crash the dispatch worker's oldest-
            # head selection and brick the whole session
            raise ValueError("submit_many needs at least one slice")
        total = sum(r.n for r in reqs)
        with self._cv:
            if self._stop or self._draining:
                raise RuntimeError("batcher is closed")
            if self._pending_rows + total > self.queue_rows:
                self.stats.count("requests_shed")
                raise ServingQueueFull(
                    f"serving queue full: {self._pending_rows} rows queued, "
                    f"request of {total} exceeds serving_queue_rows="
                    f"{self.queue_rows}")
            self.stats.count("requests_total")
            self.stats.count("rows_total", total)
            if key not in self._queues:
                self._queues[key] = deque()
            self._queues[key].extend(reqs)
            self._runners[key] = _KeyState(runner, fallback, on_error,
                                           per_device, device_ok)
            self._pending_rows += total
            self.stats.set_queue_depth(self._pending_rows)
            self._cv.notify_all()
        return reqs

    def wait(self, req: _Request, timeout_s: float) -> np.ndarray:
        if not req.done.wait(timeout_s):
            # the caller is gone: mark the queued slices so the worker
            # sheds them instead of burning device time on a result
            # nobody will read (goodput under overload)
            req.abandoned = True
            self.stats.count("requests_timeout")
            raise ServingTimeout(
                f"request of {req.n} rows not served within "
                f"{timeout_s * 1e3:.0f} ms")
        if req.error is not None:
            # failed requests stay out of the latency window: fast-
            # failing error streams would otherwise drag p50/p99 down
            # exactly while the service is erroring
            raise req.error
        self.stats.record_latency(time.monotonic() - req.t_submit)
        return req.result

    # ------------------------------------------------------------------
    def _window_s(self) -> float:
        if self._draining:
            return 0.0  # flush immediately: nothing new is coming
        if self.window_fn is not None:
            try:
                return max(float(self.window_fn()), 0.0)
            except Exception:  # pragma: no cover - defensive
                return self.max_wait_s
        return self.max_wait_s

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._queues:
                    if self._draining and self._pending_rows == 0:
                        # flushed AND every lane ran dry (_pending_rows
                        # counts in-flight work; _batch_done notifies):
                        # report drain completion, then park (close()
                        # wakes us to exit)
                        self._drained.set()
                    self._cv.wait()
                if self._stop and not self._queues:
                    self._drained.set()
                    return
                # serve the key whose head request has waited longest
                key = min(self._queues,
                          key=lambda k: self._queues[k][0].t_submit)
                dq = self._queues[key]
                rows = sum(r.n for r in dq)
                deadline = dq[0].t_submit + self._window_s()
                now = time.monotonic()
                if rows < self.max_batch_rows and now < deadline \
                        and not self._stop and not self._draining:
                    # hold the batch open for more coalescing
                    self._cv.wait(deadline - now)
                    continue
                batch = []
                take = 0
                dropped = 0
                t_pop = time.monotonic()
                while dq and (not batch
                              or take + dq[0].n <= self.max_batch_rows):
                    r = dq.popleft()
                    if r.abandoned:
                        dropped += r.n
                        r.done.set()
                        continue
                    if r.deadline is not None and t_pop > r.deadline:
                        # expired IN QUEUE: cancel before device time —
                        # counted apart from dispatch-wait timeouts,
                        # and ONCE per logical request however many
                        # slices it was split into (requests_total is
                        # per-request too; the ratio must stay sane)
                        dropped += r.n
                        if not r.group.get("expired"):
                            r.group["expired"] = True
                            self.stats.count("requests_expired")
                        r.error = ServingExpired(
                            f"request of {r.n} rows expired in queue "
                            f"({(t_pop - r.t_submit) * 1e3:.0f} ms past "
                            "submit, deadline exceeded)")
                        r.done.set()
                        continue
                    # queue wait = submit -> dispatch start: the number
                    # that separates "the device is slow" from "the
                    # queue is deep" when p99 climbs
                    self.stats.record_queue_wait(t_pop - r.t_submit)
                    batch.append(r)
                    take += r.n
                ks = self._runners[key]
                if not dq:
                    # drop the drained queue AND its runner: a stale
                    # runner closure would pin its ModelEntry (packed
                    # device forest included) long past LRU eviction
                    del self._queues[key]
                    del self._runners[key]
                # only dropped rows leave the system here; dispatched
                # rows stay in _pending_rows until _batch_done
                self._pending_rows -= dropped
                self.stats.set_queue_depth(self._pending_rows)
            if batch:
                # hand off OUTSIDE the cv: load reads and put() take the
                # worker's own lock (never nested with self._cv)
                self._pick_worker(ks).put(ks, batch, take)

    def _pick_worker(self, ks: _KeyState) -> _DeviceWorker:
        """Least-loaded routing (queued + in-flight rows) over the
        workers whose device the entry reports routable; a runner that
        is not per-device pins to worker 0 (single serialized lane —
        the pre-fleet semantics raw runners and tests rely on).  When
        EVERY device is filtered out the router falls back to all of
        them: the dispatch path's own breaker/failover machinery gets
        to decide, rather than the batch dying in queue."""
        workers = self._workers
        if not ks.per_device or len(workers) == 1:
            return workers[0]
        eligible = workers
        if ks.device_ok is not None:
            try:
                ok = [w for w in workers if ks.device_ok(w.index)]
            except Exception:  # pragma: no cover - defensive
                ok = []
            if ok:
                eligible = ok
        return min(eligible, key=lambda w: w.load())

    def _batch_done(self, rows: int) -> None:
        """A dispatched batch finished (served, failed over, or
        errored): its rows leave the system and the drain/admission
        accounting re-checks."""
        with self._cv:
            self._pending_rows -= int(rows)
            self.stats.set_queue_depth(self._pending_rows)
            self._cv.notify_all()

    # ------------------------------------------------------------------
    def _dispatch(self, runner, X, worker: _DeviceWorker):
        """One runner call, bounded by dispatch_timeout_s when armed.

        A hang is indistinguishable from slow device work from inside
        the worker thread, so the bounded form runs the runner on the
        worker's serial helper thread and abandons the WAIT on expiry
        (the helper keeps running; try_submit refuses new device work
        until it finishes, so an abandoned dispatch never overlaps a
        fresh one ON THAT DEVICE — refused batches fail over to the
        walker and the breaker keeps later requests off the device
        path; sibling lanes are untouched).  Returns (ok,
        value_or_exc)."""
        lockcheck.check_dispatch("batcher.dispatch")
        if self.dispatch_timeout_s <= 0:
            try:
                return True, runner(X)
            except BaseException as exc:
                return False, exc
        sub = worker.dispatcher.try_submit(runner, X)
        if sub is None:
            # a previously-abandoned dispatch still owns the device:
            # NOT a new timeout (dispatch_timeouts counts real expiries)
            return False, ServingTimeout(
                f"dispatch of {X.shape[0]} rows refused: a prior "
                "dispatch is still running past its watchdog deadline")
        done, box = sub
        if not done.wait(self.dispatch_timeout_s):
            self.stats.count("dispatch_timeouts")
            return False, ServingTimeout(
                f"dispatch of {X.shape[0]} rows hung past "
                f"{self.dispatch_timeout_s * 1e3:.0f} ms "
                "(serving_dispatch_timeout_ms)")
        if "exc" in box:
            return False, box["exc"]
        return True, box["out"]

    def _run(self, ks: _KeyState, batch, device: int = 0,
             worker: Optional[_DeviceWorker] = None) -> None:
        from .. import obs

        X = batch[0].X if len(batch) == 1 else \
            np.concatenate([r.X for r in batch], axis=0)
        rows = sum(r.n for r in batch)
        # per-device runners get told which device lane they landed on
        call = (lambda Xb: ks.runner(Xb, device=device)) if ks.per_device \
            else ks.runner
        t0 = time.monotonic()
        out = None
        err = None
        try:
            with obs.span("serve/dispatch", rows=int(X.shape[0]),
                          device=int(device)):
                ok, val = self._dispatch(call, X, worker)
            if not ok:
                # device-path failure (raise OR hang): report to the
                # registry health hook, then fail the BATCH over to the
                # fallback runner (native walker) so riders still get
                # answers.  on_error may veto (False = caller error,
                # e.g. malformed rows raise identically on both paths
                # and must not mask as a device fallback)
                failover = ks.fallback is not None
                if ks.on_error is not None:
                    try:
                        # per-device runners report WHICH device failed
                        # so the right replica's breaker is fed
                        verdict = (ks.on_error(val, device=device)
                                   if ks.per_device else ks.on_error(val))
                        failover = bool(verdict) and failover
                    except Exception:  # pragma: no cover - defensive
                        pass
                if not failover:
                    raise val
                self.stats.count("dispatch_failovers")
                with obs.span("serve/failover", rows=int(X.shape[0])):
                    out = ks.fallback(X)
            else:
                out = val
        except BaseException as exc:  # delivered to every waiter
            err = exc
        finally:
            wall = time.monotonic() - t0
            self.stats.record_dispatch(wall)
            if worker is not None:
                worker.note(rows, wall)
                self.stats.note_device_dispatch(device, rows)
        # rows leave the system BEFORE any waiter wakes: a caller
        # returning from wait() must observe the freed queue capacity
        self._batch_done(rows)
        if err is not None:
            for r in batch:
                r.error = err
                r.done.set()
            return
        off = 0
        for r in batch:
            # axis-0 slice works for [n] and [n, k] outputs alike; padded
            # launch rows were already cut off inside the ops layer
            r.result = out[off:off + r.n]
            off += r.n
            r.done.set()
