"""Micro-batching queue: coalesce concurrent predicts into one launch.

Sustained accelerator throughput comes from the batching layer above the
kernel, not the kernel itself (arXiv:1806.11248, arXiv:2005.09148): a
stream of small independent predict requests must ride a handful of
fixed launch shapes instead of paying one dispatch (or worse, one
compile) each.  The batcher:

* queues requests per **batch key** — (model, predict options) — so only
  result-compatible requests ever share a launch,
* holds an under-filled batch open up to the ADAPTIVE coalescing window
  (`window_fn`, the admission controller's SLO-coupled value between
  `serving_min_wait_ms` and `serving_max_wait_ms`; static
  `serving_max_wait_ms` without a controller), dispatching early once
  `max_batch_rows` rows have coalesced,
* runs batches on ONE worker thread (device access is serialized; jit
  caches and packed-forest tables never see concurrent mutation),
* scatters each request's row slice back and wakes its caller,
* sheds load at admission time: past `queue_rows` queued rows new
  requests fail immediately with `ServingQueueFull` instead of growing
  an unbounded backlog,
* **cancels expired requests in queue**: a request whose propagated
  deadline (`X-Deadline-Ms`) passes while it waits is answered with
  `ServingExpired` at pop time and never reaches the device — under
  overload, device seconds go to requests that can still make their
  budget (counted `requests_expired`, separate from the
  `requests_timeout` dispatch-wait expiries),
* **fails over a dying dispatch**: a runner that raises — or hangs past
  `dispatch_timeout_s` — reports to `on_error` (the registry's health
  hook feeding the per-entry CircuitBreaker) and the batch re-runs on
  the `fallback` runner (the native host walker) instead of failing
  every rider.  (The registry's own runner already absorbs plain
  raises internally — `ModelEntry.predict` serves the batch via the
  walker and feeds the breaker itself — so for that runner this layer
  is the HANG backstop plus a second line for anything that escapes;
  for raw runners it is the only one.)  An abandoned dispatch keeps
  running on the serial helper thread, which refuses new device work
  until it finishes — device calls never overlap,
* **drains**: `drain()` closes admission (`RuntimeError` on submit;
  the session maps it to 503 + Retry-After upstream), flushes every
  queued batch, and `close()` joins the worker — zero requests lost,
  none answered twice (each `_Request.done` fires exactly once).

Row-bucket padding itself happens in the ops layer
(`ops.predict.row_bucket` via `gbdt._chunked_device_scores`) — the
batcher only bounds *batch composition*; the registry entry accounts the
resulting launch shape against the compile cache.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Hashable, Optional

import numpy as np

from ..utils import lockcheck
from .stats import ServingStats


class ServingQueueFull(RuntimeError):
    """Admission control rejected the request (queue at capacity)."""

    http_status = 503


class ServingTimeout(TimeoutError):
    """The request waited past its serving_timeout_ms budget."""

    http_status = 504


class ServingExpired(ServingTimeout):
    """The request's propagated deadline passed while it sat in queue;
    it was cancelled before burning device time.  Subclasses
    ServingTimeout (same 504 surface) but counts separately
    (`requests_expired` vs `requests_timeout`)."""

    http_status = 504


class _Request:
    __slots__ = ("X", "n", "done", "result", "error", "t_submit",
                 "abandoned", "deadline", "group")

    def __init__(self, X: np.ndarray, deadline: Optional[float] = None,
                 group: Optional[dict] = None):
        self.X = X
        self.n = int(X.shape[0])
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.abandoned = False  # caller timed out; skip, don't compute
        self.deadline = deadline  # absolute monotonic expiry (or None)
        # shared across the slices of one LOGICAL request, so per-
        # request counters (requests_expired) count once however many
        # slices carry the deadline
        self.group = group if group is not None else {}


class _KeyState:
    """Per-batch-key dispatch plumbing: the runner plus its failover."""

    __slots__ = ("runner", "fallback", "on_error")

    def __init__(self, runner, fallback=None, on_error=None):
        self.runner = runner
        self.fallback = fallback
        self.on_error = on_error


class _SerialDispatcher:
    """ONE long-lived helper thread that runs device dispatches for the
    watchdog path.  Serialization is the point: a dispatch the watchdog
    abandoned (slow or wedged) keeps running here, and `try_submit`
    refuses new device work until it finishes — so two device calls can
    never overlap (the jit caches / packed tables single-writer
    invariant survives abandonment), and the refused batches fail over
    to the host walker instead.  A long-lived thread also keeps
    thread-spawn churn off the per-batch hot path."""

    def __init__(self):
        self._lock = lockcheck.make_lock("serving.dispatcher")
        self._work = None
        self._have = threading.Event()
        self._busy = False
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while True:
            self._have.wait()
            with self._lock:
                work, self._work = self._work, None
                self._have.clear()
            if work is None:
                continue
            runner, X, box, done = work
            try:
                box["out"] = runner(X)
            except BaseException as exc:  # delivered to the waiter
                box["exc"] = exc
            finally:
                done.set()
                with self._lock:
                    self._busy = False

    def try_submit(self, runner, X):
        """(done_event, box), or None while the previous (abandoned)
        dispatch is still running — the caller fails over."""
        with self._lock:
            if self._busy:
                return None
            self._busy = True
            box: dict = {}
            done = threading.Event()
            self._work = (runner, X, box, done)
            self._have.set()
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serving-dispatch",
                    daemon=True)
                self._thread.start()
        return done, box


class MicroBatcher:
    """Bounded coalescing queue + single dispatch worker."""

    def __init__(self, max_batch_rows: int = 4096, max_wait_ms: float = 2.0,
                 queue_rows: int = 65536,
                 stats: Optional[ServingStats] = None,
                 window_fn: Optional[Callable[[], float]] = None,
                 dispatch_timeout_ms: float = 0.0):
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.queue_rows = max(int(queue_rows), 1)
        self.stats = stats if stats is not None else ServingStats()
        # adaptive coalescing window: consulted per batch; None = static
        self.window_fn = window_fn
        self.dispatch_timeout_s = max(float(dispatch_timeout_ms), 0.0) / 1e3
        self._cv = threading.Condition()
        self._dispatcher = _SerialDispatcher()
        self._queues: "OrderedDict[Hashable, deque]" = OrderedDict()
        self._runners: "dict[Hashable, _KeyState]" = {}
        self._pending_rows = 0
        self._stop = False
        self._draining = False
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "MicroBatcher":
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._draining = False
                self._drained.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serving-batcher",
                    daemon=True)
                self._thread.start()
        return self

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Close admission and flush: new submits raise, the worker
        dispatches every queued batch, then parks.  Returns True when
        the flush completed inside `timeout_s` (False = still flushing;
        nothing is lost either way, the worker keeps going).  Safe to
        call twice; `close()` implies it."""
        with self._cv:
            self._draining = True
            if not self._queues and (self._thread is None
                                     or not self._thread.is_alive()):
                self._drained.set()
            self._cv.notify_all()
        if self._thread is None or not self._thread.is_alive():
            # no worker: queued requests can never flush; report state
            with self._cv:
                return not self._queues
        return self._drained.wait(timeout_s)

    @property
    def draining(self) -> bool:
        return self._draining

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._draining = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------------
    def submit(self, key: Hashable, runner: Callable[[np.ndarray], np.ndarray],
               X: np.ndarray, **kw) -> _Request:
        """Enqueue one request; returns a handle for `wait`.

        `runner(X_batch)` must be row-independent: request i's rows in a
        coalesced batch produce the same values they would alone (the
        bin-space traversal is, per construction)."""
        return self.submit_many(key, runner, [X], **kw)[0]

    def submit_many(self, key: Hashable,
                    runner: Callable[[np.ndarray], np.ndarray],
                    slices, deadline: Optional[float] = None,
                    fallback: Optional[Callable] = None,
                    on_error: Optional[Callable] = None) -> list:
        """Enqueue the slices of ONE logical request atomically:
        admission is all-or-nothing (a mid-request shed would leave
        already-queued slices burning device time for a caller that
        already got ServingQueueFull), and the counters see one request.

        deadline: absolute monotonic expiry propagated from the caller
        (X-Deadline-Ms); slices still queued past it are cancelled at
        pop time instead of dispatched.  fallback/on_error: the
        device-failover hooks (see module docstring)."""
        group: dict = {}
        reqs = [_Request(X, deadline, group) for X in slices]
        if not reqs:
            # an empty deque would crash the dispatch worker's oldest-
            # head selection and brick the whole session
            raise ValueError("submit_many needs at least one slice")
        total = sum(r.n for r in reqs)
        with self._cv:
            if self._stop or self._draining:
                raise RuntimeError("batcher is closed")
            if self._pending_rows + total > self.queue_rows:
                self.stats.count("requests_shed")
                raise ServingQueueFull(
                    f"serving queue full: {self._pending_rows} rows queued, "
                    f"request of {total} exceeds serving_queue_rows="
                    f"{self.queue_rows}")
            self.stats.count("requests_total")
            self.stats.count("rows_total", total)
            if key not in self._queues:
                self._queues[key] = deque()
            self._queues[key].extend(reqs)
            self._runners[key] = _KeyState(runner, fallback, on_error)
            self._pending_rows += total
            self.stats.set_queue_depth(self._pending_rows)
            self._cv.notify_all()
        return reqs

    def wait(self, req: _Request, timeout_s: float) -> np.ndarray:
        if not req.done.wait(timeout_s):
            # the caller is gone: mark the queued slices so the worker
            # sheds them instead of burning device time on a result
            # nobody will read (goodput under overload)
            req.abandoned = True
            self.stats.count("requests_timeout")
            raise ServingTimeout(
                f"request of {req.n} rows not served within "
                f"{timeout_s * 1e3:.0f} ms")
        if req.error is not None:
            # failed requests stay out of the latency window: fast-
            # failing error streams would otherwise drag p50/p99 down
            # exactly while the service is erroring
            raise req.error
        self.stats.record_latency(time.monotonic() - req.t_submit)
        return req.result

    # ------------------------------------------------------------------
    def _window_s(self) -> float:
        if self._draining:
            return 0.0  # flush immediately: nothing new is coming
        if self.window_fn is not None:
            try:
                return max(float(self.window_fn()), 0.0)
            except Exception:  # pragma: no cover - defensive
                return self.max_wait_s
        return self.max_wait_s

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._queues:
                    if self._draining:
                        # flushed: report drain completion, then park
                        # (close() wakes us to exit)
                        self._drained.set()
                    self._cv.wait()
                if self._stop and not self._queues:
                    self._drained.set()
                    return
                # serve the key whose head request has waited longest
                key = min(self._queues,
                          key=lambda k: self._queues[k][0].t_submit)
                dq = self._queues[key]
                rows = sum(r.n for r in dq)
                deadline = dq[0].t_submit + self._window_s()
                now = time.monotonic()
                if rows < self.max_batch_rows and now < deadline \
                        and not self._stop and not self._draining:
                    # hold the batch open for more coalescing
                    self._cv.wait(deadline - now)
                    continue
                batch = []
                take = 0
                dropped = 0
                t_pop = time.monotonic()
                while dq and (not batch
                              or take + dq[0].n <= self.max_batch_rows):
                    r = dq.popleft()
                    if r.abandoned:
                        dropped += r.n
                        r.done.set()
                        continue
                    if r.deadline is not None and t_pop > r.deadline:
                        # expired IN QUEUE: cancel before device time —
                        # counted apart from dispatch-wait timeouts,
                        # and ONCE per logical request however many
                        # slices it was split into (requests_total is
                        # per-request too; the ratio must stay sane)
                        dropped += r.n
                        if not r.group.get("expired"):
                            r.group["expired"] = True
                            self.stats.count("requests_expired")
                        r.error = ServingExpired(
                            f"request of {r.n} rows expired in queue "
                            f"({(t_pop - r.t_submit) * 1e3:.0f} ms past "
                            "submit, deadline exceeded)")
                        r.done.set()
                        continue
                    # queue wait = submit -> dispatch start: the number
                    # that separates "the device is slow" from "the
                    # queue is deep" when p99 climbs
                    self.stats.record_queue_wait(t_pop - r.t_submit)
                    batch.append(r)
                    take += r.n
                ks = self._runners[key]
                if not dq:
                    # drop the drained queue AND its runner: a stale
                    # runner closure would pin its ModelEntry (packed
                    # device forest included) long past LRU eviction
                    del self._queues[key]
                    del self._runners[key]
                self._pending_rows -= take + dropped
                self.stats.set_queue_depth(self._pending_rows)
            if batch:
                self._run(ks, batch)

    # ------------------------------------------------------------------
    def _dispatch(self, runner, X):
        """One runner call, bounded by dispatch_timeout_s when armed.

        A hang is indistinguishable from slow device work from inside
        this thread, so the bounded form runs the runner on the serial
        helper thread and abandons the WAIT on expiry (the helper keeps
        running; try_submit refuses new device work until it finishes,
        so an abandoned dispatch never overlaps a fresh one — refused
        batches fail over to the walker and the breaker keeps later
        requests off the device path).  Returns (ok, value_or_exc)."""
        lockcheck.check_dispatch("batcher.dispatch")
        if self.dispatch_timeout_s <= 0:
            try:
                return True, runner(X)
            except BaseException as exc:
                return False, exc
        sub = self._dispatcher.try_submit(runner, X)
        if sub is None:
            # a previously-abandoned dispatch still owns the device:
            # NOT a new timeout (dispatch_timeouts counts real expiries)
            return False, ServingTimeout(
                f"dispatch of {X.shape[0]} rows refused: a prior "
                "dispatch is still running past its watchdog deadline")
        done, box = sub
        if not done.wait(self.dispatch_timeout_s):
            self.stats.count("dispatch_timeouts")
            return False, ServingTimeout(
                f"dispatch of {X.shape[0]} rows hung past "
                f"{self.dispatch_timeout_s * 1e3:.0f} ms "
                "(serving_dispatch_timeout_ms)")
        if "exc" in box:
            return False, box["exc"]
        return True, box["out"]

    def _run(self, ks: _KeyState, batch) -> None:
        from .. import obs

        X = batch[0].X if len(batch) == 1 else \
            np.concatenate([r.X for r in batch], axis=0)
        t0 = time.monotonic()
        out = None
        try:
            with obs.span("serve/dispatch", rows=int(X.shape[0])):
                ok, val = self._dispatch(ks.runner, X)
            if not ok:
                # device-path failure (raise OR hang): report to the
                # registry health hook, then fail the BATCH over to the
                # fallback runner (native walker) so riders still get
                # answers.  on_error may veto (False = caller error,
                # e.g. malformed rows raise identically on both paths
                # and must not mask as a device fallback)
                failover = ks.fallback is not None
                if ks.on_error is not None:
                    try:
                        failover = bool(ks.on_error(val)) and failover
                    except Exception:  # pragma: no cover - defensive
                        pass
                if not failover:
                    raise val
                self.stats.count("dispatch_failovers")
                with obs.span("serve/failover", rows=int(X.shape[0])):
                    out = ks.fallback(X)
            else:
                out = val
        except BaseException as exc:  # delivered to every waiter
            for r in batch:
                r.error = exc
                r.done.set()
            return
        finally:
            self.stats.record_dispatch(time.monotonic() - t0)
        off = 0
        for r in batch:
            # axis-0 slice works for [n] and [n, k] outputs alike; padded
            # launch rows were already cut off inside the ops layer
            r.result = out[off:off + r.n]
            off += r.n
            r.done.set()
