"""Fleet placement: which devices hold which model's serving tables.

ISSUE 19 multiplies the serving runtime across every local device: the
registry replicates each model's packed forest onto a per-model device
set (default: all local devices) and the batcher grows one dispatch
worker per device.  This module owns the two pieces both sides share:

* `resolve_serving_devices` — the ONE reading of `serving_devices`
  (0 = auto: every local device on accelerator backends, a single
  device on CPU hosts, where forced virtual devices share the same
  physical cores and replication would multiply warmup compiles
  without adding throughput),
* `Replica` — one device's copy of a model: the device-resident
  quantized tables, the per-feature bin metadata pinned to the same
  device, a per-device circuit breaker (a wedged or OOMing device
  routes around, not down), and the per-bucket AOT executables,
* `PlacementTable` — the model-key -> device-index-set routing source
  of truth the batcher's least-loaded router filters against.

A replica is immutable after construction except its breaker and AOT
map; the PlacementTable is the only mutable shared state and takes its
own lock (graftlint C301 owns `_sets` to `_lock`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils import lockcheck


def resolve_serving_devices(config) -> List:
    """The device list a serving session replicates models across.

    `serving_devices` <= 0 means auto: every local device on accelerator
    backends, ONE on CPU (virtual CPU devices are the same silicon).
    An explicit count is clamped to [1, local device count] so tests can
    ask for 8 forced-host devices and a 4-chip host config degrades
    instead of erroring.
    """
    import jax

    devs = list(jax.local_devices())
    n = int(getattr(config, "serving_devices", 0) or 0)
    if n <= 0:
        n = 1 if devs[0].platform == "cpu" else len(devs)
    return devs[:max(1, min(n, len(devs)))]


class Replica:
    """One device's copy of a model's packed serving tables."""

    __slots__ = ("index", "device", "tables", "meta_dev", "scale_dev",
                 "nbytes", "breaker", "aot")

    def __init__(self, index: int, device, tables: Dict, meta_dev: Tuple,
                 breaker) -> None:
        import jax
        import jax.numpy as jnp

        self.index = index              # position in the entry's device set
        self.device = device            # jax.Device
        self.tables = tables            # full device table dict (all trees)
        self.meta_dev = meta_dev        # (num_bin, default_bin, missing_type)
        # committed unit scale: the AOT executables were lowered with a
        # device-resident f32 scale operand (serving always post-scales
        # on the host via _model_subset's divisor)
        self.scale_dev = jax.device_put(jnp.float32(1.0), device)
        self.nbytes = sum(int(v.nbytes) for v in tables.values())
        self.breaker = breaker          # per-device CircuitBreaker
        self.aot = {}                   # row bucket -> AOT executable

    def sliced(self, num_trees: int) -> Dict:
        """Device tables for the first `num_trees` trees (same slicing
        contract as `PackedForest.device`: every key but the shared
        `cat_words` pool narrows; `leaf_scale` is per-tree too)."""
        total = int(self.tables["init_node"].shape[0])
        if num_trees < 0 or num_trees >= total:
            return self.tables
        return {k: (v if k == "cat_words" else v[:num_trees])
                for k, v in self.tables.items()}

    def healthy(self) -> bool:
        """Routable right now: the per-device breaker admits traffic
        (closed, or open-and-cooled-down enough for a half-open probe)."""
        return self.breaker.allow()


class PlacementTable:
    """model key -> device-index tuple; the fleet routing truth.

    The batcher's router asks `devices_for(key)` on every batch; the
    registry writes rows on load/unload.  Lock-ordered leaf: nothing is
    called while `_lock` is held.
    """

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("serving.placement")
        self._sets: Dict[str, Tuple[int, ...]] = {}

    def place(self, key: str, device_indices) -> None:
        with self._lock:
            self._sets[key] = tuple(int(i) for i in device_indices)

    def remove(self, key: str) -> None:
        with self._lock:
            self._sets.pop(key, None)

    def devices_for(self, key: str) -> Optional[Tuple[int, ...]]:
        with self._lock:
            return self._sets.get(key)

    def snapshot(self) -> Dict[str, Tuple[int, ...]]:
        with self._lock:
            return dict(self._sets)
