"""AOT-compiled serving cold starts: serialized bucket executables.

A cold replica — process restart, continual-learning promotion, LRU
re-load — used to pay one XLA compile per bucket-ladder launch shape
before its first request could meet the p99 SLO.  This module closes
that gap: at load time every (device, row-bucket) launch of the
class-scores kernel is `lower().compile()`d once and serialized beside
the model through `jax.experimental.serialize_executable`; the next
load of the same model `deserialize_and_load`s the executables and the
first served batch runs with ZERO new compiled programs (the compile
ledger proves it — the AOT path never enters the jit cache at all).

Cache layout: one file per (model signature, device, bucket) under
`serving_aot_cache_dir` (or `<tpu_compile_cache_dir>/serving_aot` when
only the PR-4 persistent XLA cache is configured):

    <sig16>-d<device_id>-b<bucket>.aotx

`<sig16>` hashes the PR-6 `warm_signature` (chunk, batch rows, bucket
policy, feature count, class count, depth bucket, table shapes+dtypes
— quantization precision changes the dtypes, so each precision keys
its own executables) together with the jax version, backend platform
and device kind.  Serialized executables are pinned to the device they
compiled on, hence the `d<device_id>` coordinate.  Invalidation is by
construction: any drift in the signature, jax version or device simply
hashes to a file that does not exist.  A corrupted or stale blob fails
`load_bucket` and the registry degrades to a logged warm compile — a
bad cache entry can slow a load, never fail it.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from typing import Optional

_MAGIC = "lgbm-aotx-v1"


def cache_dir(config) -> Optional[str]:
    """The AOT executable cache root, or None when AOT serving is off.

    `serving_aot_cache_dir` wins; otherwise ride beside the persistent
    XLA compile cache when one is configured."""
    explicit = str(getattr(config, "serving_aot_cache_dir", "") or "")
    if explicit:
        return explicit
    base = str(getattr(config, "tpu_compile_cache_dir", "") or "")
    if base:
        return os.path.join(base, "serving_aot")
    return None


def signature_hash(warm_sig, device) -> str:
    """16-hex content key for one model's executables on one device
    kind.  Everything that can change the compiled program is in the
    preimage; the device id rides in the file name (executables are
    device-pinned), the kind in the hash (a TPU blob must never match
    a CPU host)."""
    import jax

    payload = repr((warm_sig, jax.__version__, device.platform,
                    getattr(device, "device_kind", "")))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def bucket_path(dirpath: str, sig: str, device_id: int, bucket: int) -> str:
    return os.path.join(dirpath, f"{sig}-d{int(device_id)}-b{int(bucket)}.aotx")


def compile_bucket(tables_dev, num_feature: int, bucket: int, meta_dev,
                   depth_bucket: int, k: int):
    """One warm AOT compile of the class-scores kernel for `bucket`
    rows on the device holding `tables_dev`.

    Goes through `_class_scores_kernel.lower().compile()` — NOT the
    kernel's `__call__` — so neither the jit cache nor the compile
    ledger grows a program; the returned executable is invoked directly
    by the replica predict path."""
    import jax
    import jax.numpy as jnp

    from ..ops.predict import _class_scores_kernel

    sharding = jax.sharding.SingleDeviceSharding(
        next(iter(tables_dev["init_node"].devices())))
    bins_aval = jax.ShapeDtypeStruct((int(bucket), int(num_feature)),
                                     jnp.int32, sharding=sharding)
    nb, db, mt = meta_dev
    scale = jax.device_put(jnp.float32(1.0), sharding)
    lowered = _class_scores_kernel.lower(
        tables_dev, bins_aval, nb, db, mt, scale,
        depth=int(depth_bucket), has_cat=bool(
            int(tables_dev["cat_words"].shape[0]) > 1), k=int(k))
    return lowered.compile()


def save_bucket(path: str, compiled) -> None:
    """Serialize one compiled executable atomically (tmp+rename, like
    every other artifact writer in the repo — a torn .aotx must never
    exist under the canonical name)."""
    from jax.experimental import serialize_executable as se

    blob, in_tree, out_tree = se.serialize(compiled)
    payload = pickle.dumps({"magic": _MAGIC, "blob": blob,
                            "in_tree": in_tree, "out_tree": out_tree},
                           protocol=pickle.HIGHEST_PROTOCOL)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_bucket(path: str):
    """Deserialize one executable; raises on ANY corruption/staleness
    (missing file, bad magic, unpicklable tree, runtime rejection) —
    the caller turns that into a logged warm compile, never a failed
    model load."""
    from jax.experimental import serialize_executable as se

    with open(path, "rb") as f:
        payload = pickle.loads(f.read())
    if not isinstance(payload, dict) or payload.get("magic") != _MAGIC:
        raise ValueError(f"not a {_MAGIC} executable: {path}")
    return se.deserialize_and_load(payload["blob"], payload["in_tree"],
                                   payload["out_tree"])
