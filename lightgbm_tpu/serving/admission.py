"""Adaptive admission control: AIMD against the serving SLO.

The static `serving_queue_rows` bound sheds only when the backlog is
already catastrophic — by then every queued request is doomed to miss
its latency budget anyway.  The admission controller closes the loop
the PR-10 metrics registry opened: it projects the latency a NEW
request would see (recent queue-wait p99 + dispatch p95, read from the
same histograms `GET /metrics` exports) and runs AIMD on an
*admitted-rows level* against the `serving_slo_ms` target:

* **multiplicative decrease** — the projection exceeding the SLO cuts
  the level by `serving_aimd_backoff` (x0.5 by default): offered load
  beyond what the device clears inside the SLO is refused at the door
  with 429 + `Retry-After`, instead of queueing into guaranteed
  timeouts.  Goodput stays near the saturation plateau.
* **additive increase** — a comfortable projection (< 70% of the SLO)
  grows the level by `serving_aimd_step_rows` up to the hard
  `serving_queue_rows` ceiling, re-probing for capacity after load
  drops or a device recovers.

**Priority classes** shed asymmetrically: each class admits only while
the queue sits under its fraction of the level (low 60%, normal 85%,
high 100%), so under pressure `low` traffic sheds first and `high`
keeps flowing until the controller itself is saturated.

**Batch-window coupling**: the same projection drives the batcher's
coalescing window — slack latency widens the window toward
`serving_max_wait_ms` (better fill, fewer launches), pressure narrows
it toward `serving_min_wait_ms` (lowest queueing delay) — replacing
the single static window.

**Drain** rides the same gate: `begin_drain()` flips every subsequent
admit into `ServingDraining` (503 + `Retry-After`) while in-flight
work flushes.

The controller is O(1) per admit (a monotonic-clock interval gate in
front of the histogram read) and entirely host-side: no jit programs,
no device work — the compile-stability retrace gate pins that.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..utils import lockcheck
from .stats import ServingStats

# class -> fraction of the admitted-rows level it may fill before
# shedding; admission is priority-ordered by construction
PRIORITY_FACTORS: Dict[str, float] = {"high": 1.0, "normal": 0.85,
                                      "low": 0.6}
DEFAULT_PRIORITY = "normal"


class ServingOverloaded(RuntimeError):
    """Adaptive admission shed: the SLO projection refuses this class.

    Maps to HTTP 429 (the caller should back off `retry_after_s` and
    retry) — distinct from `ServingQueueFull`'s 503, which is the hard
    `serving_queue_rows` capacity wall."""

    http_status = 429

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServingDraining(RuntimeError):
    """The session is draining: admission is closed while in-flight
    batches flush.  Maps to HTTP 503 + `Retry-After` (another replica
    should take the traffic)."""

    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


def resolve_priority(value) -> str:
    """'high' | 'normal' | 'low' from a request field/header; unknown
    spellings raise (a typo silently mapped to 'normal' would strip the
    caller's intended protection)."""
    if value is None:
        return DEFAULT_PRIORITY
    s = str(value).strip().lower()
    if s == "":
        return DEFAULT_PRIORITY
    if s not in PRIORITY_FACTORS:
        raise ValueError(
            f"unknown priority {value!r}; known: "
            f"{sorted(PRIORITY_FACTORS)}")
    return s


class AdmissionController:
    """AIMD admitted-rows level + adaptive batch window + drain gate."""

    def __init__(self, stats: ServingStats, slo_ms: float,
                 queue_rows: int, max_batch_rows: int,
                 interval_ms: float = 100.0, step_rows: int = 512,
                 backoff: float = 0.5, min_wait_ms: float = 0.0,
                 max_wait_ms: float = 2.0, retry_after_ms: float = 1000.0,
                 enabled: bool = True, devices: int = 1):
        self.stats = stats
        self.slo_s = max(float(slo_ms), 1e-3) / 1e3
        self.queue_rows = max(int(queue_rows), 1)
        # the floor: one full batch always stays admissible, so a level
        # crushed by a long outage still serves probes that re-grow it
        self.min_level = min(max(int(max_batch_rows), 1), self.queue_rows)
        self.interval_s = max(float(interval_ms), 1.0) / 1e3
        # the additive re-probe scales with dispatch lanes (ISSUE 19):
        # an 8-device fleet regains admitted capacity 8x as fast after
        # a shed, matching its 8x drain rate — the multiplicative
        # backoff stays per-SLO, capacity-independent
        self.step_rows = max(int(step_rows), 1) * max(int(devices), 1)
        self.backoff = min(max(float(backoff), 0.05), 0.95)
        self.min_wait_s = max(float(min_wait_ms), 0.0) / 1e3
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.retry_after_s = max(float(retry_after_ms), 0.0) / 1e3
        self.enabled = bool(enabled)
        self._lock = lockcheck.make_lock("serving.admission")
        self._level = float(self.queue_rows)   # start fully open
        self._window_s = self.max_wait_s
        self._projection_s = 0.0
        self._next_update = time.monotonic()
        self._draining = False
        self._publish()

    # ------------------------------------------------------------------
    def admit(self, rows: int, priority: str, queue_depth_rows: int,
              ) -> None:
        """Gate one request of `rows` at `priority` against the current
        level; raises ServingDraining / ServingOverloaded to shed.  The
        hard `serving_queue_rows` wall stays in the batcher
        (`ServingQueueFull`) — this gate only ever sheds EARLIER."""
        if self._draining:
            self.stats.count("requests_drain_rejected")
            raise ServingDraining(
                "serving session is draining; admission closed",
                self.retry_after_s)
        if not self.enabled:
            return
        self._maybe_update()
        factor = PRIORITY_FACTORS.get(priority, PRIORITY_FACTORS["normal"])
        allowed = max(self._level * factor, float(self.min_level) * factor)
        if queue_depth_rows + rows > allowed:
            self.stats.count("requests_overload")
            raise ServingOverloaded(
                f"admission shed ({priority}): {queue_depth_rows} rows "
                f"queued + {rows} exceeds the adaptive level "
                f"{allowed:.0f} (SLO projection "
                f"{self._projection_s * 1e3:.1f} ms vs serving_slo_ms="
                f"{self.slo_s * 1e3:.0f})", self.retry_after_s)

    # ------------------------------------------------------------------
    def _maybe_update(self) -> None:
        now = time.monotonic()
        if now < self._next_update:
            return
        with self._lock:
            if now < self._next_update:  # lost the race: already updated
                return
            self._next_update = now + self.interval_s
            qwait, dispatch, n = self.stats.recent_wait_profile()
            proj = qwait + dispatch
            self._projection_s = proj
            if n >= 8:
                if proj > self.slo_s:
                    self._level = max(self._level * self.backoff,
                                      float(self.min_level))
                elif proj < 0.7 * self.slo_s:
                    self._level = min(self._level + self.step_rows,
                                      float(self.queue_rows))
            else:
                # too few recent dispatches to judge: re-open additively
                # (an idle service must not stay clamped forever)
                self._level = min(self._level + self.step_rows,
                                  float(self.queue_rows))
            # batch window rides the same projection: slack -> wide
            # (batch fill), pressure -> narrow (queueing delay)
            slack = min(max(1.0 - proj / self.slo_s, 0.0), 1.0)
            self._window_s = (self.min_wait_s
                              + (self.max_wait_s - self.min_wait_s) * slack)
            self._publish()

    def _publish(self) -> None:
        self.stats.set_admission(self._level, self._window_s,
                                 self._projection_s)

    # ------------------------------------------------------------------
    def batch_window_s(self) -> float:
        """Current adaptive coalescing window for the batcher."""
        if not self.enabled:
            return self.max_wait_s
        return self._window_s

    # ------------------------------------------------------------------
    def begin_drain(self) -> None:
        # under the controller lock like every other state flip: the
        # bool write is GIL-atomic, but lock discipline is the declared
        # invariant (graftlint C301 enforces the ownership map), and an
        # undeclared exception here would rot into a real race the next
        # time drain grows a second field
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def snapshot(self) -> Dict:
        return {
            "admission_enabled": self.enabled,
            "admission_level_rows": round(self._level, 1),
            "batch_window_ms": round(self._window_s * 1e3, 3),
            "slo_projection_ms": round(self._projection_s * 1e3, 3),
            "draining": self._draining,
        }
