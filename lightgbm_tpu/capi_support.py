"""Python-side backend for the native C API shim (src/capi).

The reference stacks ctypes-Python ON TOP of a C++ core (reference
python-package/lightgbm/basic.py:24-47 binding src/c_api.cpp).  This
framework's engine is Python/JAX (the XLA program IS the native core), so
the C ABI layer inverts: `lib_lightgbm_tpu.so` (src/capi/
lightgbm_tpu_c_api.cpp) embeds CPython and routes each `LGBM_*` call here.
Handles crossing the ABI are integer ids into `_registry`; raw buffer
pointers are converted with ctypes/numpy on this side so the C++ stays a
thin marshalling layer.

Mirrors the behavior of reference src/c_api.cpp:98-320 (Booster wrapper)
and the dataset creation entry points (reference include/LightGBM/
c_api.h:52-256).
"""

from __future__ import annotations

import ctypes
import itertools
import threading
from typing import Dict, List, Tuple

import numpy as np

# An embedded C consumer may be this process's first jax user: a dead
# tunneled backend would hang the first LGBM_* call inside backend init,
# so probe-or-pin BEFORE the engine import (same guard as the CLI).
from .utils.backend import ensure_backend_or_cpu as _ensure

_ensure()

from .basic import Booster, Dataset
from .config import Config

# C_API_DTYPE_* (reference include/LightGBM/c_api.h:26-35)
DTYPE_FLOAT32 = 0
DTYPE_FLOAT64 = 1
DTYPE_INT32 = 2
DTYPE_INT64 = 3
DTYPE_INT8 = 4

_CTYPES = {
    DTYPE_FLOAT32: ctypes.c_float,
    DTYPE_FLOAT64: ctypes.c_double,
    DTYPE_INT32: ctypes.c_int32,
    DTYPE_INT64: ctypes.c_int64,
    DTYPE_INT8: ctypes.c_int8,
}

# C_API_PREDICT_* (c_api.h:37-40)
PREDICT_NORMAL = 0
PREDICT_RAW_SCORE = 1
PREDICT_LEAF_INDEX = 2
PREDICT_CONTRIB = 3

_registry: Dict[int, object] = {}
_handles = itertools.count(1)
_lock = threading.Lock()
# pinned arrays returned by dataset_get_field: the caller reads the raw
# pointer after we return, so the array must outlive the call
_field_pins: Dict[Tuple[int, str], np.ndarray] = {}


def _put(obj) -> int:
    with _lock:
        h = next(_handles)
        _registry[h] = obj
    return h


def _get(handle: int):
    try:
        return _registry[handle]
    except KeyError:
        raise ValueError(f"invalid handle {handle}") from None


def free_handle(handle: int) -> None:
    with _lock:
        _registry.pop(handle, None)
        for key in [k for k in _field_pins if k[0] == handle]:
            _field_pins.pop(key, None)


def _params_dict(params_str: str) -> dict:
    return Config.str_to_map(params_str or "")


def _mat_from_ptr(ptr: int, data_type: int, nrow: int, ncol: int,
                  is_row_major: int) -> np.ndarray:
    ct = _CTYPES[data_type]
    buf = ctypes.cast(ptr, ctypes.POINTER(ct))
    arr = np.ctypeslib.as_array(buf, shape=(nrow * ncol,))
    if is_row_major:
        return arr.reshape(nrow, ncol).astype(np.float64)
    return arr.reshape(ncol, nrow).T.astype(np.float64)


def _vec_from_ptr(ptr: int, data_type: int, n: int) -> np.ndarray:
    ct = _CTYPES[data_type]
    buf = ctypes.cast(ptr, ctypes.POINTER(ct))
    return np.ctypeslib.as_array(buf, shape=(n,)).copy()


# ---------------------------------------------------------------- dataset
def dataset_create_from_mat(ptr: int, data_type: int, nrow: int, ncol: int,
                            is_row_major: int, params: str,
                            ref_handle: int) -> int:
    X = _mat_from_ptr(ptr, data_type, nrow, ncol, is_row_major)
    ref = _get(ref_handle) if ref_handle else None
    ds = Dataset(X, reference=ref, params=_params_dict(params))
    ds.construct()
    return _put(ds)


def dataset_create_from_csr(indptr_ptr: int, indptr_type: int, indices_ptr: int,
                            data_ptr: int, data_type: int, nindptr: int,
                            nelem: int, num_col: int, params: str,
                            ref_handle: int) -> int:
    X = _scipy_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                   data_type, nindptr, nelem, num_col)
    ref = _get(ref_handle) if ref_handle else None
    ds = Dataset(X, reference=ref, params=_params_dict(params))
    ds.construct()
    return _put(ds)


def dataset_create_from_file(filename: str, params: str,
                             ref_handle: int) -> int:
    p = _params_dict(params)
    from .io.parser import load_text_file

    X, y, weight, group, _, _ = load_text_file(
        filename, label_column=str(p.get("label_column", "")))
    ref = _get(ref_handle) if ref_handle else None
    ds = Dataset(X, label=y, weight=weight, group=group, reference=ref,
                 params=p)
    ds.construct()
    return _put(ds)


def dataset_num_data(handle: int) -> int:
    return int(_get(handle).num_data())


def dataset_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())


def dataset_set_field(handle: int, name: str, ptr: int, n: int,
                      data_type: int) -> None:
    ds = _get(handle)
    data = _vec_from_ptr(ptr, data_type, n) if n > 0 else None
    ds.set_field(name, data)


def dataset_get_field(handle: int, name: str) -> Tuple[int, int, int]:
    """(ptr, len, dtype) of the pinned field array; (0, 0, -1) if absent."""
    ds = _get(handle)
    data = ds.get_field(name)
    if data is None:
        return 0, 0, -1
    if name == "group":
        arr = np.ascontiguousarray(data, dtype=np.int32)
        dt = DTYPE_INT32
    else:
        arr = np.ascontiguousarray(data, dtype=np.float32)
        dt = DTYPE_FLOAT32
    _field_pins[(handle, name)] = arr
    return arr.ctypes.data, int(arr.shape[0]), dt


def dataset_save_binary(handle: int, filename: str) -> None:
    ds = _get(handle)
    ds.construct()
    ds._inner.save_binary(filename)


# ---------------------------------------------------------------- booster
def booster_create(train_handle: int, params: str) -> int:
    ds = _get(train_handle)
    bst = Booster(params=_params_dict(params), train_set=ds)
    return _put(bst)


def booster_create_from_modelfile(filename: str) -> Tuple[int, int]:
    bst = Booster(model_file=filename)
    return _put(bst), int(bst.current_iteration())


def booster_load_from_string(model_str: str) -> Tuple[int, int]:
    bst = Booster(model_str=model_str)
    return _put(bst), int(bst.current_iteration())


def booster_add_valid(bh: int, dh: int) -> None:
    bst = _get(bh)
    n = len(bst._valid_names) + 1
    bst.add_valid(_get(dh), f"valid_{n}")


def booster_num_classes(bh: int) -> int:
    return int(_get(bh).num_model_per_iteration())


def booster_update(bh: int) -> int:
    finished = _get(bh).update()
    return 1 if finished else 0


def booster_update_custom(bh: int, grad_ptr: int, hess_ptr: int) -> int:
    bst = _get(bh)
    n = bst._train_set.num_data() * bst.num_model_per_iteration()
    grad = _vec_from_ptr(grad_ptr, DTYPE_FLOAT32, n).astype(np.float64)
    hess = _vec_from_ptr(hess_ptr, DTYPE_FLOAT32, n).astype(np.float64)
    finished = bst.update(fobj=lambda score, ds: (grad, hess))
    return 1 if finished else 0


def booster_rollback(bh: int) -> None:
    _get(bh).rollback_one_iter()


def booster_current_iteration(bh: int) -> int:
    return int(_get(bh).current_iteration())


def booster_num_total_model(bh: int) -> int:
    return int(_get(bh).num_trees())


def booster_num_feature(bh: int) -> int:
    return int(_get(bh).num_feature())


def _eval_results(bst: Booster, data_idx: int) -> List[Tuple[str, float]]:
    if data_idx == 0:
        res = bst.eval_train()
    else:
        res = [r for r in bst.eval_valid()
               if r[0] == f"valid_{data_idx}"]
    return [(r[1], float(r[2])) for r in res]


def booster_eval_counts(bh: int) -> int:
    return len(_eval_results(_get(bh), 0))


def booster_get_eval(bh: int, data_idx: int, out_ptr: int) -> int:
    res = _eval_results(_get(bh), data_idx)
    out = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_double)),
        shape=(len(res),))
    for i, (_, v) in enumerate(res):
        out[i] = v
    return len(res)


def booster_get_eval_names(bh: int) -> str:
    return "\n".join(name for name, _ in _eval_results(_get(bh), 0))


def booster_predict_for_mat(bh: int, ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, predict_type: int,
                            num_iteration: int, params: str,
                            out_ptr: int) -> int:
    X = _mat_from_ptr(ptr, data_type, nrow, ncol, is_row_major)
    return _predict_into(_get(bh), X, predict_type, num_iteration, out_ptr,
                         params)


def booster_calc_num_predict(bh: int, nrow: int, predict_type: int,
                             num_iteration: int) -> int:
    bst = _get(bh)
    k = bst.num_model_per_iteration()
    if predict_type == PREDICT_LEAF_INDEX:
        ni = num_iteration if num_iteration > 0 else max(
            1, bst.num_trees() // max(k, 1))
        return nrow * k * ni
    if predict_type == PREDICT_CONTRIB:
        return nrow * k * (bst.num_feature() + 1)
    return nrow * k


def booster_save_model(bh: int, num_iteration: int, filename: str) -> None:
    ni = num_iteration if num_iteration > 0 else None
    _get(bh).save_model(filename, num_iteration=ni)


def booster_save_to_string(bh: int, num_iteration: int) -> str:
    ni = num_iteration if num_iteration > 0 else None
    return _get(bh).model_to_string(num_iteration=ni)


def booster_dump_model(bh: int, num_iteration: int) -> str:
    import json

    ni = num_iteration if num_iteration > 0 else None
    return json.dumps(_get(bh).dump_model(num_iteration=ni))


def booster_feature_importance(bh: int, num_iteration: int,
                               importance_type: int, out_ptr: int) -> int:
    bst = _get(bh)
    itype = "split" if importance_type == 0 else "gain"
    imp = np.asarray(bst.feature_importance(importance_type=itype),
                     dtype=np.float64)
    out = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_double)),
        shape=(imp.shape[0],))
    out[:] = imp
    return int(imp.shape[0])


# ---------------------------------------------------------------- network
_network: Dict[str, int] = {"num_machines": 1, "rank": 0}


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    """Record the network config; the actual collective transport is the
    jax.distributed / mesh layer (reference LGBM_NetworkInit c_api.h:999
    maps to Linkers; here ICI/DCN collectives are compiled into the XLA
    program, so init only validates and stores the topology request)."""
    if num_machines > 1:
        from .parallel.mesh import available_devices

        if num_machines > available_devices():
            raise ValueError(
                f"num_machines={num_machines} exceeds available devices")
    _network["num_machines"] = int(num_machines)
    _network["rank"] = 0


def network_free() -> None:
    _network["num_machines"] = 1
    _network["rank"] = 0


def booster_reset_parameter(bh: int, params: str) -> None:
    _get(bh).reset_parameter(_params_dict(params))


def booster_merge(bh: int, other_bh: int) -> None:
    """Append the other booster's trees (reference GBDT::MergeFrom,
    gbdt.h:60)."""
    other = _get(other_bh)
    _get(bh)._driver.merge_from_model_string(other.model_to_string())


def booster_shuffle_models(bh: int, start: int, end: int) -> None:
    _get(bh).shuffle_models(start, end)


def booster_get_leaf_value(bh: int, tree_idx: int, leaf_idx: int) -> float:
    drv = _get(bh)._driver
    drv._materialize()  # trees are built lazily from device records
    return float(drv.models[tree_idx].leaf_value[leaf_idx])


def booster_set_leaf_value(bh: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    drv = _get(bh)._driver
    drv._materialize()
    drv.models[tree_idx].set_leaf_value(leaf_idx, float(val))
    drv._invalidate_tables()


def booster_predict_for_file(bh: int, data_filename: str, has_header: int,
                             predict_type: int, num_iteration: int,
                             params: str, result_filename: str) -> None:
    """Reference LGBM_BoosterPredictForFile (c_api.h:644): parse, predict,
    write the text result file like the CLI predictor."""
    from .config import Config
    from .io.parser import load_text_file

    bst = _get(bh)
    p = _params_dict(params)
    ni = num_iteration if num_iteration > 0 else None
    kw = {}
    if predict_type == PREDICT_RAW_SCORE:
        kw["raw_score"] = True
    elif predict_type == PREDICT_LEAF_INDEX:
        kw["pred_leaf"] = True
    elif predict_type == PREDICT_CONTRIB:
        kw["pred_contrib"] = True
    pcfg = Config({**bst.params, **p})
    for key in ("pred_early_stop", "pred_early_stop_freq",
                "pred_early_stop_margin", "predict_disable_shape_check"):
        kw[key] = getattr(pcfg, key)
    X = load_text_file(data_filename,
                       label_column=str(pcfg.label_column or ""),
                       header=bool(has_header) or None)[0]
    out = np.asarray(bst.predict(X, num_iteration=ni, **kw))
    with open(result_filename, "w") as f:
        if out.ndim == 1:
            for v in out:
                f.write(f"{v:g}\n")
        else:
            for row in out:
                f.write("\t".join(f"{v:g}" for v in row) + "\n")


def dataset_set_feature_names(dh: int, names: str) -> None:
    ds = _get(dh)
    parts = names.split("\t") if names else []
    nf = ds._inner.num_total_features if ds._inner is not None else None
    if nf is not None and len(parts) != nf:
        raise ValueError(
            f"{len(parts)} feature names for {nf} features")
    ds.feature_name = parts
    if ds._inner is not None:
        ds._inner.feature_names = list(parts)


def dataset_get_feature_names(dh: int) -> str:
    ds = _get(dh)
    if ds._inner is not None:
        return "\t".join(str(n) for n in ds._inner.feature_names)
    fn = ds.feature_name
    return "\t".join(fn) if isinstance(fn, (list, tuple)) else ""


def dataset_get_subset(dh: int, idx_ptr: int, n_idx: int,
                       params: str) -> int:
    """Row subset sharing the parent's mappers (reference
    Dataset::CopySubset via LGBM_DatasetGetSubset, c_api.h:286)."""
    ds = _get(dh)
    idx = np.ctypeslib.as_array(
        ctypes.cast(idx_ptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(n_idx,)).copy()
    sub = ds.subset(idx, params=_params_dict(params) or None)
    return _put(sub)


def booster_num_model_per_iteration(bh: int) -> int:
    return booster_num_classes(bh)


def booster_get_feature_names(bh: int) -> str:
    return "\t".join(str(n) for n in _get(bh).feature_name())


def _densify_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                 data_type, nindptr, nelem, num_col):
    """CSR pointers -> dense [nrow, num_col] f64 (block-bounded callers
    only: the streaming push path; whole-matrix ingest goes through
    _scipy_csr)."""
    indptr = _vec_from_ptr(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _vec_from_ptr(indices_ptr, DTYPE_INT32, nelem).astype(np.int64)
    vals = _vec_from_ptr(data_ptr, data_type, nelem).astype(np.float64)
    nrow = nindptr - 1
    X = np.zeros((nrow, num_col), np.float64)
    row_of = np.repeat(np.arange(nrow), np.diff(indptr))
    # duplicate coordinates must SUM like scipy toarray(), not
    # last-write-win — the scipy and scipy-less paths must bin alike
    np.add.at(X, (row_of, indices), vals)
    return X


def _warn_no_scipy(kind: str) -> None:
    from .utils.log import Log

    Log.warning(f"scipy is unavailable; the {kind} C-API path densifies "
                "the matrix on the host (O(nrow*ncol) memory instead of "
                "O(nnz)) — install scipy for sparse ingest at scale")


def _scipy_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
               data_type, nindptr, nelem, num_col):
    """CSR pointers -> scipy.sparse.csr_matrix, O(nnz), no densify.
    Without scipy the path falls back to the dense decode with a loud
    warning rather than an ImportError — the C ABI caller cannot see a
    Python traceback."""
    try:
        from scipy import sparse as sps
    except ImportError:
        _warn_no_scipy("CSR")
        return _densify_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                            data_type, nindptr, nelem, num_col)

    indptr = _vec_from_ptr(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _vec_from_ptr(indices_ptr, DTYPE_INT32, nelem).astype(np.int32)
    vals = _vec_from_ptr(data_ptr, data_type, nelem).astype(np.float64)
    return sps.csr_matrix((vals, indices, indptr),
                          shape=(nindptr - 1, num_col))


def _predict_kwargs(predict_type: int) -> dict:
    if predict_type == PREDICT_RAW_SCORE:
        return {"raw_score": True}
    if predict_type == PREDICT_LEAF_INDEX:
        return {"pred_leaf": True}
    if predict_type == PREDICT_CONTRIB:
        return {"pred_contrib": True}
    return {}


def _predict_into(bst, X, predict_type: int, num_iteration: int,
                  out_ptr: int, params: str = "") -> int:
    ni = num_iteration if num_iteration > 0 else None
    kw = _predict_kwargs(predict_type)
    if params:
        # forward the predict-time keys from the C params string
        # (reference c_api.cpp predict paths parse the full Config)
        pcfg = Config({**bst.params, **_params_dict(params)})
        for key in ("pred_early_stop", "pred_early_stop_freq",
                    "pred_early_stop_margin", "predict_disable_shape_check"):
            kw[key] = getattr(pcfg, key)
    pred = np.asarray(
        bst.predict(X, num_iteration=ni, **kw),
        dtype=np.float64).reshape(-1)
    out = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_double)),
        shape=(pred.shape[0],))
    out[:] = pred
    return int(pred.shape[0])


def booster_predict_for_csr(bh: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            predict_type: int, num_iteration: int,
                            params: str, out_ptr: int) -> int:
    """Sparse rows ride Booster.predict's chunked-densify path
    (reference c_api.h:644 PredictForCSR)."""
    X = _scipy_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                   data_type, nindptr, nelem, num_col)
    return _predict_into(_get(bh), X, predict_type, num_iteration, out_ptr,
                         params)


def dataset_create_from_mats(ptrs_ptr: int, data_type: int, nrows_ptr: int,
                             nmat: int, ncol: int, is_row_major: int,
                             params: str, ref_handle: int) -> int:
    """Stack several row-major blocks into one dataset (reference
    LGBM_DatasetCreateFromMats, c_api.h:160)."""
    # read the pointer array as raw uint64 words: numpy's buffer
    # protocol has no PEP-3118 code for void*
    ptrs = np.ctypeslib.as_array(
        ctypes.cast(ptrs_ptr, ctypes.POINTER(ctypes.c_uint64)),
        shape=(nmat,))
    nrows = np.ctypeslib.as_array(
        ctypes.cast(nrows_ptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(nmat,))
    blocks = [_mat_from_ptr(int(ptrs[i]), data_type, int(nrows[i]), ncol,
                            is_row_major)
              for i in range(nmat)]
    X = np.vstack(blocks)
    ref = _get(ref_handle) if ref_handle else None
    ds = Dataset(X, reference=ref, params=_params_dict(params))
    ds.construct()
    return _put(ds)


def _score_state(drv, data_idx: int):
    """data_idx -> maintained score state (0 = train, i+1 = valid i)."""
    if data_idx == 0:
        return drv.train_scores
    if 0 < data_idx <= len(drv.valid_scores):
        return drv.valid_scores[data_idx - 1]
    raise IndexError(f"no dataset at data_idx {data_idx}")


def booster_get_num_predict(bh: int, data_idx: int) -> int:
    """Prediction count for dataset data_idx (reference
    LGBM_BoosterGetNumPredict, c_api.h:608)."""
    st = _score_state(_get(bh)._driver, data_idx)
    return int(st.scores.shape[0] * st.scores.shape[1])


def booster_get_predict(bh: int, data_idx: int, out_ptr: int) -> int:
    """Converted predictions for dataset data_idx (reference
    LGBM_BoosterGetPredict -> GBDT::GetPredictAt, which applies the
    objective's ConvertOutput transform; written class-major)."""
    drv = _get(bh)._driver
    drv._materialize()
    st = _score_state(drv, data_idx)
    scores = st.numpy()
    if drv.objective is not None:
        scores = np.asarray(drv.objective.convert_output(scores),
                            np.float64).reshape(scores.shape)
    scores = scores.reshape(-1)
    out = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_double)),
        shape=(scores.shape[0],))
    out[:] = scores
    return int(scores.shape[0])


def dataset_update_param(dh: int, params: str) -> None:
    """Merge new params, rejecting changes to bin-defining keys once
    constructed (reference Dataset::ResetConfig, dataset.cpp:395-400)."""
    ds = _get(dh)
    new = _params_dict(params)
    if ds._inner is not None:
        frozen = ("max_bin", "max_bin_by_feature", "bin_construct_sample_cnt",
                  "min_data_in_bin", "use_missing", "zero_as_missing",
                  "categorical_feature", "forcedbins_filename")
        # compare EFFECTIVE values (current config incl. defaults), so
        # restating a default is the no-op the reference accepts
        cur = Config(ds.params)
        eff = Config({**ds.params, **new})
        for k in frozen:
            if k in new and getattr(eff, k) != getattr(cur, k):
                raise ValueError(
                    f"cannot change {k} after the dataset is constructed")
    ds.params.update(new)


def _make_streaming_dataset(reference, num_total_row: int, ncol: int,
                            params: dict) -> "Dataset":
    """NaN-filled pending buffer whose rows arrive via PushRows; refuses
    to construct until every allocated row was pushed (the reference's
    FinishLoad contract — unpushed rows would silently train as NaN)."""
    buf = np.full((int(num_total_row), ncol), np.nan, np.float64)
    ds = Dataset(buf, reference=reference, params=params)
    ds._pushed = np.zeros(int(num_total_row), bool)
    ds._pushed_complete = False
    orig_construct = ds.construct

    def _guarded_construct():
        if not ds._pushed_complete and ds._inner is None:
            missing = int((~ds._pushed).sum())
            raise RuntimeError(
                f"{missing} of {len(ds._pushed)} rows never pushed")
        return orig_construct()

    ds.construct = _guarded_construct
    return ds


def dataset_create_by_reference(ref_handle: int, num_total_row: int) -> int:
    """Allocate an empty row buffer aligned with `ref` for streaming
    construction via PushRows (reference c_api.h:266-311)."""
    ref = _get(ref_handle)
    ref.construct()
    ds = _make_streaming_dataset(ref, num_total_row,
                                 ref._inner.num_total_features,
                                 dict(ref.params))
    return _put(ds)


def dataset_push_rows(dh: int, ptr: int, data_type: int, nrow: int,
                      ncol: int, start_row: int) -> None:
    ds = _get(dh)
    if ds._inner is not None:
        raise RuntimeError("cannot push rows after construction")
    block = _mat_from_ptr(ptr, data_type, nrow, ncol, 1)
    ds.data[start_row:start_row + nrow, :] = block
    ds._pushed[start_row:start_row + nrow] = True
    if bool(ds._pushed.all()):
        # every allocated row arrived: the dataset may construct (the
        # reference's FinishLoad moment)
        ds._pushed_complete = True


def dataset_dump_text(dh: int, filename: str) -> None:
    """Debug text dump: header plus per-row label and binned values
    (reference LGBM_DatasetDumpText, c_api.h:316)."""
    ds = _get(dh)
    ds.construct()
    inner = ds._inner
    with open(filename, "w") as f:
        f.write(f"num_data: {inner.num_data}\n")
        f.write(f"num_features: {inner.num_features}\n")
        f.write("feature_names: " + "\t".join(inner.feature_names) + "\n")
        label = inner.metadata.label
        if label is None:
            label = np.zeros(inner.num_data, np.float64)
        for i in range(inner.num_data):
            row = "\t".join(str(int(b)) for b in inner.bins[i])
            f.write(f"{label[i]:g}\t{row}\n")


def _scipy_csc(col_ptr_p, col_ptr_type, indices_ptr, data_ptr, data_type,
               ncol_ptr, nelem, num_row):
    """CSC pointers -> scipy.sparse.csc_matrix, O(nnz), no densify
    (reference LGBM_DatasetCreateFromCSC keeps columns sparse,
    c_api.cpp CSC path / src/io/sparse_bin.hpp:73).  Falls back to a
    dense decode with a warning when scipy is absent — see _scipy_csr."""
    col_ptr = _vec_from_ptr(col_ptr_p, col_ptr_type, ncol_ptr).astype(np.int64)
    indices = _vec_from_ptr(indices_ptr, DTYPE_INT32, nelem).astype(np.int64)
    vals = _vec_from_ptr(data_ptr, data_type, nelem).astype(np.float64)
    try:
        from scipy import sparse as sps
    except ImportError:
        _warn_no_scipy("CSC")
        X = np.zeros((num_row, ncol_ptr - 1), np.float64)
        col_of = np.repeat(np.arange(ncol_ptr - 1), np.diff(col_ptr))
        np.add.at(X, (indices, col_of), vals)  # duplicates sum, as scipy
        return X
    return sps.csc_matrix((vals, indices.astype(np.int32), col_ptr),
                          shape=(num_row, ncol_ptr - 1))


def dataset_create_from_csc(col_ptr_p: int, col_ptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncol_ptr: int, nelem: int, num_row: int,
                            params: str, ref_handle: int) -> int:
    X = _scipy_csc(col_ptr_p, col_ptr_type, indices_ptr, data_ptr,
                   data_type, ncol_ptr, nelem, num_row)
    ref = _get(ref_handle) if ref_handle else None
    ds = Dataset(X, reference=ref, params=_params_dict(params))
    ds.construct()
    return _put(ds)


def booster_predict_for_csc(bh: int, col_ptr_p: int, col_ptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncol_ptr: int, nelem: int, num_row: int,
                            predict_type: int, num_iteration: int,
                            params: str, out_ptr: int) -> int:
    X = _scipy_csc(col_ptr_p, col_ptr_type, indices_ptr, data_ptr,
                   data_type, ncol_ptr, nelem, num_row)
    return _predict_into(_get(bh), X, predict_type, num_iteration, out_ptr,
                         params)


def dataset_add_features_from(dh: int, other_dh: int) -> None:
    """Merge `other`'s features into `dh` column-wise (reference
    Dataset::AddFeaturesFrom via LGBM_DatasetAddFeaturesFrom,
    c_api.h:297): delegates to Dataset.add_features_from (basic.py)."""
    _get(dh).add_features_from(_get(other_dh))


def booster_reset_training_data(bh: int, dh: int) -> None:
    bst = _get(bh)
    ds = _get(dh)
    ds.construct()
    bst._driver.reset_training_data(ds._inner)
    bst._train_set = ds


def booster_predict_for_mats(bh: int, ptrs_ptr: int, data_type: int,
                             nrows_ptr: int, nmat: int, ncol: int,
                             predict_type: int, num_iteration: int,
                             params: str, out_ptr: int) -> int:
    ptrs = np.ctypeslib.as_array(
        ctypes.cast(ptrs_ptr, ctypes.POINTER(ctypes.c_uint64)),
        shape=(nmat,))
    nrows = np.ctypeslib.as_array(
        ctypes.cast(nrows_ptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(nmat,))
    X = np.vstack([_mat_from_ptr(int(ptrs[i]), data_type, int(nrows[i]),
                                 ncol, 1)
                   for i in range(nmat)])
    return _predict_into(_get(bh), X, predict_type, num_iteration, out_ptr,
                         params)


def booster_refit(bh: int, leaf_preds_ptr: int, nrow: int,
                  ncol: int) -> None:
    """Reference LGBM_BoosterRefit (c_api.h:493 -> GBDT::RefitTree):
    re-fit leaf values on the CURRENT training data given a [nrow, ncol]
    leaf-assignment matrix (one column per model)."""
    drv = _get(bh)._driver
    drv._materialize()
    if drv.train_data is None:
        raise ValueError("refit by leaf predictions needs a booster with "
                         "training data attached")
    if nrow != drv.train_data.num_data:
        raise ValueError(f"leaf_preds has {nrow} rows for "
                         f"{drv.train_data.num_data} training rows")
    if ncol != len(drv.models):
        raise ValueError(f"leaf_preds has {ncol} columns for "
                         f"{len(drv.models)} models")
    leaf_preds = np.ctypeslib.as_array(
        ctypes.cast(leaf_preds_ptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(nrow, ncol)).copy()
    cfg = drv.config or Config({})
    obj = drv.objective
    if obj is None:
        from .models.objectives import create_objective_from_model_string

        obj = create_objective_from_model_string(
            drv.loaded_params.get("objective", ""))
    if obj is None:
        raise ValueError("cannot refit without an objective")
    if getattr(obj, "metadata", None) is None:
        obj.init(drv.train_data.metadata, drv.train_data.num_data)
    drv._refit_by_leaf_preds(leaf_preds, obj,
                             float(cfg.refit_decay_rate), cfg)


def dataset_push_rows_by_csr(dh: int, indptr_ptr: int, indptr_type: int,
                             indices_ptr: int, data_ptr: int,
                             data_type: int, nindptr: int, nelem: int,
                             num_col: int, start_row: int) -> None:
    ds = _get(dh)
    if ds._inner is not None:
        raise RuntimeError("cannot push rows after construction")
    block = _densify_csr(indptr_ptr, indptr_type, indices_ptr, data_ptr,
                         data_type, nindptr, nelem, num_col)
    nrow = block.shape[0]
    ds.data[start_row:start_row + nrow, :] = block
    ds._pushed[start_row:start_row + nrow] = True
    if bool(ds._pushed.all()):
        ds._pushed_complete = True


def dataset_create_from_sampled_column(sample_ptrs: int, indices_ptrs: int,
                                       ncol: int, num_per_col_ptr: int,
                                       num_sample_row: int,
                                       num_total_row: int,
                                       params: str) -> int:
    """Reference LGBM_DatasetCreateFromSampledColumn (c_api.h:69):
    mappers from per-column value samples, rows pushed afterwards.
    Unsampled entries are zero, like the reference's sparse sampling."""
    sp = np.ctypeslib.as_array(
        ctypes.cast(sample_ptrs, ctypes.POINTER(ctypes.c_uint64)),
        shape=(ncol,))
    ip = np.ctypeslib.as_array(
        ctypes.cast(indices_ptrs, ctypes.POINTER(ctypes.c_uint64)),
        shape=(ncol,))
    counts = np.ctypeslib.as_array(
        ctypes.cast(num_per_col_ptr, ctypes.POINTER(ctypes.c_int32)),
        shape=(ncol,))
    sample = np.zeros((int(num_sample_row), int(ncol)), np.float64)
    for c in range(int(ncol)):
        m = int(counts[c])
        if m == 0:
            continue
        vals = _vec_from_ptr(int(sp[c]), DTYPE_FLOAT64, m)
        rows = _vec_from_ptr(int(ip[c]), DTYPE_INT32, m).astype(np.int64)
        sample[rows, c] = vals
    p = _params_dict(params)
    # mapper donor found ONCE on the sample, the near-unsplittable filter
    # scaled against the FULL row count; constraints derive from the
    # donor's own used-feature set, so nothing is swapped post-hoc
    from .io.dataset import Metadata, TrainingData, _parse_column_spec

    donor_td = TrainingData()
    donor_td.config = Config(p)
    donor_td.num_data = int(num_sample_row)
    donor_td.num_total_features = int(ncol)
    donor_td.feature_names = [f"Column_{i}" for i in range(int(ncol))]
    cat = _parse_column_spec(donor_td.config.categorical_feature,
                             donor_td.feature_names)
    donor_td._find_mappers(sample, donor_td.config, cat or [], {},
                           total_rows=int(num_total_row))
    donor_td._set_constraints(donor_td.config)
    donor_td.metadata = Metadata(int(num_sample_row))
    donor = Dataset.__new__(Dataset)
    donor.data = None
    donor.label = None
    donor.reference = None
    donor.weight = donor.group = donor.init_score = None
    donor.feature_name = "auto"
    donor.categorical_feature = p.get("categorical_feature", "auto")
    donor.params = dict(p)
    donor.free_raw_data = True
    donor.used_indices = None
    donor._inner = donor_td
    ds = _make_streaming_dataset(donor, int(num_total_row), int(ncol), p)
    return _put(ds)
