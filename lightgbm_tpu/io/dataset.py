"""Binned training data: the TPU-facing data representation.

The reference stores bins in per-group `Bin` columns with EFB bundling and
sparse/dense specializations (reference src/io/dataset.cpp:265, include/
LightGBM/feature_group.h:37).  TPU-first, the binned matrix is instead ONE
fixed-shape `[n_rows, n_features]` integer array resident in HBM — the analog
of the GPU learner's `Feature4` packing (reference src/treelearner/
gpu_tree_learner.cpp:354-527) — because the histogram kernel consumes all
features of a row block at once via one-hot contractions on the MXU.

`TrainingData` owns:
  * per-feature `BinMapper`s (shared with validation sets, like the reference's
    `CreateValid` alignment, dataset.h:501),
  * the host binned matrix (uint8/uint16) and its device copy,
  * `Metadata` (labels / weights / query boundaries / init scores,
    reference src/io/metadata.cpp).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import os

import numpy as np

from ..config import Config
from .bin_mapper import BinMapper, BinType, MissingType, K_ZERO_THRESHOLD
from .parser import load_text_file


def _is_scipy_sparse(data) -> bool:
    """scipy.sparse matrix/array, detected without importing scipy."""
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def _parallel_columns(fn, count: int, config: Optional[Config]) -> None:
    """Fan per-column ingest work out on a thread pool — the analog of
    the reference's OpenMP-parallel `ConstructBinMappersFromData`
    (dataset_loader.cpp:696).  numpy's sort / searchsorted release the
    GIL on large arrays, so column work genuinely overlaps.  Output is
    deterministic: every column writes only its own pre-allocated slot,
    and `fn` is pure per column."""
    workers = int(getattr(config, "num_threads", 0) or 0) if config else 0
    if workers <= 0:
        workers = os.cpu_count() or 1
    workers = min(workers, count)
    if workers <= 1 or count <= 1:
        for j in range(count):
            fn(j)
        return
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as ex:
        # list() drains the iterator so worker exceptions propagate
        list(ex.map(fn, range(count)))


class Metadata:
    """Labels, weights, query boundaries, init scores (reference dataset.h:87)."""

    def __init__(self, num_data: int, label: Optional[np.ndarray] = None,
                 weight: Optional[np.ndarray] = None,
                 group_sizes: Optional[np.ndarray] = None,
                 init_score: Optional[np.ndarray] = None):
        self.num_data = num_data
        self.label = (np.zeros(num_data, dtype=np.float32) if label is None
                      else np.asarray(label, dtype=np.float32))
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float32)
        self.init_score = (None if init_score is None
                           else np.asarray(init_score, dtype=np.float64))
        if group_sizes is not None:
            gs = np.asarray(group_sizes, dtype=np.int64)
            self.query_boundaries = np.concatenate([[0], np.cumsum(gs)]).astype(np.int64)
            if self.query_boundaries[-1] != num_data:
                raise ValueError(
                    f"sum of query sizes ({self.query_boundaries[-1]}) != num_data ({num_data})")
        else:
            self.query_boundaries = None

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def query_weights(self) -> Optional[np.ndarray]:
        """Per-query weight = mean of row weights inside the query; None when
        rows are unweighted (reference src/io/metadata.cpp:461-470)."""
        if self.query_boundaries is None or self.weight is None:
            return None
        w = np.asarray(self.weight, np.float64)
        sums = np.add.reduceat(w, self.query_boundaries[:-1])
        return sums / np.diff(self.query_boundaries)

    def set_field(self, name: str, data: Optional[np.ndarray]) -> None:
        if name == "label":
            self.label = np.asarray(data, dtype=np.float32)
        elif name == "weight":
            self.weight = None if data is None else np.asarray(data, dtype=np.float32)
        elif name in ("group", "query"):
            if data is None:
                self.query_boundaries = None
            else:
                gs = np.asarray(data, dtype=np.int64)
                self.query_boundaries = np.concatenate([[0], np.cumsum(gs)]).astype(np.int64)
        elif name == "init_score":
            self.init_score = None if data is None else np.asarray(data, dtype=np.float64)
        else:
            raise ValueError(f"unknown field {name}")

    def get_field(self, name: str) -> Optional[np.ndarray]:
        if name == "label":
            return self.label
        if name == "weight":
            return self.weight
        if name in ("group", "query"):
            return self.query_boundaries
        if name == "init_score":
            return self.init_score
        raise ValueError(f"unknown field {name}")


def _load_forced_bins(config: Config) -> Dict[int, List[float]]:
    """Load forcedbins_filename JSON: [{"feature": i, "bin_upper_bound": [...]}]

    (reference src/io/dataset_loader.cpp:1246 GetForcedBins).
    """
    path = config.forcedbins_filename
    if not path:
        return {}
    import json
    with open(path) as f:
        entries = json.load(f)
    out: Dict[int, List[float]] = {}
    for e in entries:
        out[int(e["feature"])] = [float(x) for x in e["bin_upper_bound"]]
    return out


def _parse_column_spec(spec: str, feature_names: List[str]) -> List[int]:
    """Parse '0,1,2' or 'name:a,b,c' into column indices."""
    if not spec:
        return []
    s = str(spec)
    if s.startswith("name:"):
        names = [x.strip() for x in s[5:].split(",") if x.strip()]
        return [feature_names.index(n) for n in names if n in feature_names]
    return [int(x) for x in s.replace(";", ",").split(",") if x != ""]


class TrainingData:
    """Binned dataset + metadata. The unit the tree learners consume."""

    def __init__(self) -> None:
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.used_feature_idx: List[int] = []     # used col -> original col
        self.mappers: List[BinMapper] = []        # one per ORIGINAL column
        self._bins: Optional[np.ndarray] = None   # [n, num_used] uint8/uint16
        self._ingest_bins = None   # device-resident [n, num_used] (ops/binning)
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.config: Optional[Config] = None
        self.monotone_constraints: Optional[np.ndarray] = None  # per used feature
        self.feature_penalty: Optional[np.ndarray] = None       # per used feature
        self._device_bins = None

    # ------------------------------------------------------------------
    @property
    def bins(self) -> Optional[np.ndarray]:
        """Host binned matrix.  When ingest ran on device the host copy
        materializes LAZILY here, on first access by a host consumer
        (EFB planning, get_data, save_binary, subset) — the device fast
        path never pays for it."""
        if self._bins is None and self._ingest_bins is not None:
            self._bins = np.asarray(self._ingest_bins)
        return self._bins

    @bins.setter
    def bins(self, value: Optional[np.ndarray]) -> None:
        self._bins = value
        self._ingest_bins = None
        self._device_bins = None

    @property
    def has_bins(self) -> bool:
        """True when ANY binned representation exists (host or device).
        Check this instead of `bins is None`: the property fetch would
        force a host materialization of a device-resident matrix."""
        return self._bins is not None or self._ingest_bins is not None

    def device_ingest_bins(self):
        """The device-resident narrow-dtype bin matrix, or None when the
        host copy is authoritative (host ingest, or a consumer already
        materialized + possibly mutated through the property)."""
        return self._ingest_bins if self._bins is None else None

    @property
    def num_features(self) -> int:
        return len(self.used_feature_idx)

    @property
    def max_num_bin(self) -> int:
        if not self.used_feature_idx:
            return 1
        return max(self.mappers[i].num_bin for i in self.used_feature_idx)

    def feature_arrays(self) -> Dict[str, np.ndarray]:
        """Per-used-feature static arrays consumed by the device grower."""
        idx = self.used_feature_idx
        num_bin = np.array([self.mappers[i].num_bin for i in idx], dtype=np.int32)
        missing = np.array([int(self.mappers[i].missing_type) for i in idx], dtype=np.int32)
        default_bin = np.array([self.mappers[i].default_bin for i in idx], dtype=np.int32)
        is_categorical = np.array(
            [self.mappers[i].bin_type == BinType.CATEGORICAL for i in idx], dtype=bool)
        mono = (self.monotone_constraints if self.monotone_constraints is not None
                else np.zeros(len(idx), dtype=np.int32))
        penalty = (self.feature_penalty if self.feature_penalty is not None
                   else np.ones(len(idx), dtype=np.float32))
        return {"num_bin": num_bin, "missing_type": missing,
                "default_bin": default_bin, "is_categorical": is_categorical,
                "monotone": mono.astype(np.int32), "penalty": penalty.astype(np.float32)}

    def device_bins(self):
        """Device int32 copy of the binned matrix (cached).  Ingest that
        ran on device just widens in place — no host round trip."""
        import jax.numpy as jnp
        if self._device_bins is None:
            if self._ingest_bins is not None:
                self._device_bins = self._ingest_bins.astype(jnp.int32)
            else:
                self._device_bins = jnp.asarray(self.bins.astype(np.int32))
        return self._device_bins

    # -- reductions host consumers ask for without forcing the full
    # host matrix (the learner's layout step reads these) -------------
    def column_zero_fraction(self) -> np.ndarray:
        """Per-used-column fraction of rows at bin 0 (the EFB candidate
        gate).  Device-resident matrices reduce on device and fetch only
        the [F] counts; the division happens in f64 on the host either
        way, so the result is bit-identical to `(bins == 0).mean(0)`."""
        dev = self.device_ingest_bins()
        if dev is not None:
            import jax.numpy as jnp
            cnt = np.asarray(jnp.sum(dev == 0, axis=0, dtype=jnp.int32))
            return cnt.astype(np.float64) / max(self.num_data, 1)
        return (self.bins == 0).mean(axis=0)

    def column_nonzero_counts(self, zero_bins: np.ndarray) -> np.ndarray:
        """Per-used-column count of rows NOT at that column's zero bin
        (the sparse-storage gate).  One vectorized pass — device reduce
        when resident, row-chunked host sweep otherwise (bounds the
        boolean temporary on Bosch-shaped data)."""
        zb = np.asarray(zero_bins)
        dev = self.device_ingest_bins()
        if dev is not None:
            import jax.numpy as jnp
            return np.asarray(jnp.sum(
                dev != jnp.asarray(zb.astype(np.int32))[None, :],
                axis=0, dtype=jnp.int32)).astype(np.int64)
        bins = self.bins
        n = bins.shape[0]
        step = max((1 << 28) // max(bins.shape[1], 1), 1024)
        out = np.zeros(bins.shape[1], np.int64)
        for lo in range(0, n, step):
            out += (bins[lo:lo + step] != zb[None, :]).sum(axis=0)
        return out

    def strided_row_sample(self, quota: int) -> np.ndarray:
        """The deterministic strided row sample `bundling._stride_sample`
        would take, fetched as a host array — a device slice-gather when
        resident, so EFB planning never pulls the full matrix."""
        dev = self.device_ingest_bins()
        if dev is None:
            from .bundling import _stride_sample

            return _stride_sample(self.bins, quota)
        n = self.num_data
        if n > quota:
            step = n // quota
            return np.asarray(dev[::step][:quota])
        return np.asarray(dev)

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, X: np.ndarray, label: Optional[np.ndarray] = None,
                    config: Optional[Config] = None,
                    weight: Optional[np.ndarray] = None,
                    group_sizes: Optional[np.ndarray] = None,
                    init_score: Optional[np.ndarray] = None,
                    reference: Optional["TrainingData"] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_features: Optional[Sequence[int]] = None,
                    forced_bins: Optional[Dict[int, List[float]]] = None,
                    ) -> "TrainingData":
        """Bin a raw float matrix.

        With `reference` given, reuses its BinMappers (validation-set
        alignment, reference dataset.h:501 CreateValid).
        """
        config = config or Config()
        # arm the telemetry policy BEFORE the ingest phases run: the
        # train set constructs ahead of the GBDT driver, and its
        # sketch/binning spans must not be lost to ordering
        from .. import obs

        obs.configure_from_config(config)
        X = np.asarray(X, dtype=np.float64)
        if X.ndim != 2:
            raise ValueError("X must be 2-D")
        n, nf = X.shape
        self = cls()
        self.config = config
        self.num_data = n
        self.num_total_features = nf
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(nf)])

        from ..utils import timer

        with timer.PHASE("sketch"):
            if reference is not None:
                self._adopt_reference_mappers(reference)
            else:
                self._find_mappers_maybe_distributed(
                    X, config, categorical_features or [], forced_bins or {})

        # bin all used columns: device chunk-streamed kernel on the fast
        # path, host per-column numpy otherwise
        with timer.PHASE("binning"):
            dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
            binner = self._make_device_binner(config, dtype, n)
            if binner is not None:
                self._ingest_bins = binner.bin_matrix(X)
                self._bins = None
            else:
                bins = np.empty((n, self.num_features), dtype=dtype)

                def _bin_col(j: int) -> None:
                    col = self.used_feature_idx[j]
                    # contiguous column copy: searchsorted on a strided
                    # view costs ~40% more than the 8 MB copy saves
                    bins[:, j] = self.mappers[col].values_to_bins(
                        np.ascontiguousarray(X[:, col])).astype(
                            dtype, copy=False)

                _parallel_columns(_bin_col, self.num_features, config)
                self.bins = bins

        self.metadata = Metadata(n, label, weight, group_sizes, init_score)
        self._set_constraints(config)
        return self

    def _make_device_binner(self, config: Config, dtype, n_rows: int):
        """A ready DeviceBinner when config routes ingest to the device
        kernel (ops/binning.py), else None.  'auto' requires an
        accelerator default backend AND enough rows to amortize the
        dispatch; huge categorical id spaces fall back to host (the
        kernel's LUT is dense)."""
        from ..config import parse_tristate

        mode = parse_tristate(config.tpu_ingest_device)
        if mode == "false" or self.num_features == 0:
            return None
        if mode == "auto":
            import jax

            if (jax.default_backend() == "cpu"
                    or n_rows < int(config.tpu_ingest_min_rows)):
                return None
        from ..ops.binning import DeviceBinner

        return DeviceBinner.build(self.mappers, self.used_feature_idx,
                                  dtype, int(config.tpu_ingest_chunk_rows))

    @classmethod
    def from_sparse(cls, sp, label: Optional[np.ndarray] = None,
                    config: Optional[Config] = None,
                    weight: Optional[np.ndarray] = None,
                    group_sizes: Optional[np.ndarray] = None,
                    init_score: Optional[np.ndarray] = None,
                    reference: Optional["TrainingData"] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_features: Optional[Sequence[int]] = None,
                    forced_bins: Optional[Dict[int, List[float]]] = None,
                    ) -> "TrainingData":
        """Bin a scipy CSR/CSC matrix in O(nnz) host memory.

        The reference keeps sparse features delta-encoded end to end
        (src/io/sparse_bin.hpp:73, include/LightGBM/bin.h:472-508); the
        TPU core is a dense `[n, F]` int8/16 matrix (the histogram
        kernel's one-hot contraction wants fixed shape), so the sparse
        path's job is to reach that matrix WITHOUT ever materializing the
        `[n, F]` f64 intermediate: bin finding reads stored values off
        the CSC arrays, and binning fills each column with its zero bin
        then scatters the O(nnz) stored-value bins.
        """
        config = config or Config()
        sp = sp.tocsc()
        # non-canonical inputs (duplicate coordinates) must SUM like
        # scipy's own toarray(), not last-write-win in the bin scatter
        sp.sum_duplicates()
        n, nf = sp.shape
        self = cls()
        self.config = config
        self.num_data = n
        self.num_total_features = nf
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(nf)])

        from ..utils import timer

        with timer.PHASE("sketch"):
            if reference is not None:
                self._adopt_reference_mappers(reference)
            else:
                # sparse ingest joins the collective bin-finding path
                # directly: the feature-sharded mapper search slices CSC
                # columns and samples stored values exactly like the local
                # find (local_payload -> _find_mappers is sparse-aware)
                self._find_mappers_maybe_distributed(
                    sp, config, categorical_features or [], forced_bins or {})

        with timer.PHASE("binning"):
            dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
            bins = np.empty((n, self.num_features), dtype=dtype)
            indptr, indices, data = sp.indptr, sp.indices, sp.data
            for j, col in enumerate(self.used_feature_idx):
                m = self.mappers[col]
                lo, hi = int(indptr[col]), int(indptr[col + 1])
                # implicit zeros take the column's zero-value bin
                # (default_bin IS value_to_bin(0.0), set at find time;
                # most_freq_bin semantics fall out of it)
                colbins = np.full(n, m.default_bin, dtype=dtype)
                if hi > lo:
                    vals = np.asarray(data[lo:hi], dtype=np.float64)
                    colbins[indices[lo:hi]] = \
                        m.values_to_bins(vals).astype(dtype)
                bins[:, j] = colbins
            self.bins = bins

        self.metadata = Metadata(n, label, weight, group_sizes, init_score)
        self._set_constraints(config)
        return self

    @classmethod
    def from_file(cls, path: str, config: Optional[Config] = None,
                  reference: Optional["TrainingData"] = None) -> "TrainingData":
        config = config or Config()
        # binary fast path (reference CheckCanLoadFromBin,
        # dataset_loader.cpp:1217 + binary token check): <path>.bin skips
        # parsing and re-binning entirely
        # per-host cache presence may diverge; every host must walk the
        # same (collective) bin-finding path or the group hangs
        from .distributed_binning import (config_wants_distributed,
                                          ensure_distributed)
        from .. import obs

        obs.configure_from_config(config)
        ensure_distributed(config)
        skip_cache = config_wants_distributed(config)
        if reference is None and not skip_cache \
                and os.path.exists(path + ".bin"):
            try:
                return cls.from_binary(path + ".bin")
            except Exception as exc:
                from ..utils.log import Log

                Log.warning(f"ignoring stale binary cache {path}.bin: {exc}")
        if bool(config.two_round):
            try:
                data = cls._from_file_two_round(path, config, reference)
                if bool(config.save_binary):
                    data.save_binary(path + ".bin")
                return data
            except ValueError as exc:  # e.g. libsvm: no streaming reader
                from ..utils.log import Log

                Log.warning(f"two_round fell back to one-pass load: {exc}")
        X, y, w, group, init, names = load_text_file(
            path, label_column=config.label_column,
            header=True if config.header else None)
        cat = _parse_column_spec(config.categorical_feature, names)
        data = cls.from_matrix(X, y, config, weight=w, group_sizes=group,
                               init_score=init, reference=reference,
                               feature_names=names, categorical_features=cat,
                               forced_bins=_load_forced_bins(config))
        if bool(config.save_binary):
            data.save_binary(path + ".bin")
        return data

    @classmethod
    def _from_file_two_round(cls, path: str, config: Config,
                             reference: Optional["TrainingData"],
                             chunk_rows: int = 200_000) -> "TrainingData":
        """Two-pass streaming load (reference two_round,
        dataset_loader.cpp:188-216): pass 1 reservoir-samples
        `bin_construct_sample_cnt` rows for bin finding and counts rows;
        pass 2 streams chunks straight into the uint8/16 bin matrix.  The
        raw float matrix is never resident — peak memory drops from
        n*F*8 bytes to n*F*1 plus one chunk."""
        from .parser import TextChunkReader, load_sidecars

        reader = TextChunkReader(path, label_column=config.label_column,
                                 header=True if config.header else None,
                                 chunk_rows=chunk_rows)
        names = reader.feature_names
        sample_cnt = max(int(config.bin_construct_sample_cnt), 2)
        rng = np.random.default_rng(int(config.data_random_seed))

        # ---- pass 1: row count + algorithm-R reservoir over chunks
        # (with a reference the mappers are reused, so only the count,
        # labels, and column width are needed — no sampling) ----
        n = 0
        ncols = 0
        sample: Optional[np.ndarray] = None
        labels_parts: List[np.ndarray] = []
        for Xc, yc in reader.chunks():
            m = len(yc)
            labels_parts.append(yc)
            ncols = Xc.shape[1]
            if reference is None:
                if sample is None:
                    sample = Xc[:sample_cnt].copy()
                elif len(sample) < sample_cnt:
                    # reservoir not yet full: the chunk's LEADING rows are
                    # the next global positions < sample_cnt
                    need = sample_cnt - len(sample)
                    sample = np.vstack([sample, Xc[:need]])
                start = max(n, sample_cnt)
                if start < n + m:
                    pos = np.arange(start, n + m)
                    local = pos - n
                    accept = rng.random(len(pos)) < sample_cnt / (pos + 1.0)
                    slots = rng.integers(0, sample_cnt,
                                         size=int(accept.sum()))
                    sample[slots] = Xc[local[accept]]
            n += m
        if n == 0:
            raise ValueError(f"empty data file {path}")
        label = np.concatenate(labels_parts)

        from ..utils import timer

        self = cls()
        self.config = config
        self.num_data = n
        self.num_total_features = ncols
        self.feature_names = list(names)
        with timer.PHASE("sketch"):
            if reference is not None:
                self._adopt_reference_mappers(reference)
            else:
                cat = _parse_column_spec(config.categorical_feature, names)
                self._find_mappers_maybe_distributed(
                    sample, config, cat or [], _load_forced_bins(config),
                    total_rows=n)

        # ---- pass 2: stream rows into bins (file chunks feed the
        # device kernel directly when ingest is device-routed, so the
        # full host matrix never exists on that path either) ----
        with timer.PHASE("binning"):
            dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
            binner = self._make_device_binner(config, dtype, n)
            if binner is not None:
                # bin_stream re-chunks across reader blocks, so only the
                # file's final launch pads
                self._ingest_bins = binner.bin_stream(
                    Xc for Xc, _ in reader.chunks())
                self._bins = None
            else:
                bins = np.empty((n, self.num_features), dtype=dtype)
                row = 0
                for Xc, _ in reader.chunks():
                    m = Xc.shape[0]
                    for j, col in enumerate(self.used_feature_idx):
                        bins[row:row + m, j] = self.mappers[col] \
                            .values_to_bins(Xc[:, col]).astype(dtype)
                    row += m
                self.bins = bins

        weight, group, init_score = load_sidecars(path)
        self.metadata = Metadata(n, label, weight, group, init_score)
        self._set_constraints(config)
        return self

    # ------------------------------------------------------------------
    _BINARY_TOKEN = "lightgbm_tpu.binned.v1"

    def save_binary(self, path: str) -> None:
        """Serialize the binned dataset (reference Dataset::SaveBinaryFile,
        src/io/dataset.cpp:695): bins + mappers + metadata, so reloading
        skips parsing and bin finding."""
        import json

        md = self.metadata
        np.savez_compressed(
            path,
            token=np.frombuffer(self._BINARY_TOKEN.encode(), np.uint8),
            bins=self.bins,
            used_feature_idx=np.asarray(self.used_feature_idx, np.int64),
            num_total_features=np.int64(self.num_total_features),
            mappers=np.frombuffer(json.dumps(
                [m.to_dict() for m in self.mappers]).encode(), np.uint8),
            feature_names=np.frombuffer(
                json.dumps(self.feature_names).encode(), np.uint8),
            label=md.label,
            weight=(md.weight if md.weight is not None
                    else np.zeros(0, np.float32)),
            query_boundaries=(md.query_boundaries
                              if md.query_boundaries is not None
                              else np.zeros(0, np.int64)),
            init_score=(md.init_score if md.init_score is not None
                        else np.zeros(0, np.float64)),
            monotone=(self.monotone_constraints
                      if self.monotone_constraints is not None
                      else np.zeros(0, np.int32)),
            penalty=(self.feature_penalty
                     if self.feature_penalty is not None
                     else np.zeros(0, np.float32)))
        # numpy appends .npz; normalize to the requested name
        if not path.endswith(".npz") and os.path.exists(path + ".npz"):
            os.replace(path + ".npz", path)

    @classmethod
    def from_binary(cls, path: str) -> "TrainingData":
        import json

        from .bin_mapper import BinMapper

        with np.load(path, allow_pickle=False) as z:
            token = bytes(z["token"]).decode()
            if token != cls._BINARY_TOKEN:
                raise ValueError(f"unrecognized binary dataset token "
                                 f"{token!r}")
            self = cls()
            self.bins = z["bins"]
            self.used_feature_idx = [int(i) for i in z["used_feature_idx"]]
            self.num_total_features = int(z["num_total_features"])
            self.mappers = [BinMapper.from_dict(d) for d in
                            json.loads(bytes(z["mappers"]).decode())]
            self.feature_names = json.loads(
                bytes(z["feature_names"]).decode())
            self.num_data = int(self.bins.shape[0])
            md = Metadata(self.num_data, label=z["label"])
            if z["weight"].size:
                md.weight = z["weight"]
            if z["query_boundaries"].size:
                md.query_boundaries = z["query_boundaries"]
            if z["init_score"].size:
                md.init_score = z["init_score"]
            self.metadata = md
            if z["monotone"].size:
                self.monotone_constraints = z["monotone"]
            if z["penalty"].size:
                self.feature_penalty = z["penalty"]
        return self

    # ------------------------------------------------------------------
    def _adopt_reference_mappers(self, reference: "TrainingData") -> None:
        """Share the reference's BinMappers for validation-set alignment
        (reference dataset.h:501 CreateValid)."""
        self.mappers = reference.mappers
        self.used_feature_idx = list(reference.used_feature_idx)
        self.monotone_constraints = reference.monotone_constraints
        self.feature_penalty = reference.feature_penalty
        # eval_for_data on a freed booster (train_data dropped) can no
        # longer compare mapper identity; this flag records that the bins
        # came from SOME reference rather than a fresh find
        self.adopted_reference = True
        if reference.num_total_features != self.num_total_features:
            raise ValueError("validation data feature count mismatch")

    def _find_mappers_maybe_distributed(self, X, config, categorical,
                                        forced_bins,
                                        total_rows: Optional[int] = None
                                        ) -> None:
        """Feature-sharded multi-host bin finding when this process is
        part of a pre-partitioned jax.distributed group (reference
        dataset_loader.cpp:959-1042); plain local find otherwise.

        NO silent fallback once pre_partition requests distribution: a
        host that skipped the collective while its peers entered it would
        deadlock the group, so errors here must be loud."""
        from .distributed_binning import (config_wants_distributed,
                                          ensure_distributed,
                                          find_mappers_multihost)

        ensure_distributed(config)
        if config_wants_distributed(config):
            self.mappers = find_mappers_multihost(
                X, config, categorical, forced_bins,
                local_total_rows=total_rows,
                feature_names=self.feature_names)
            self.used_feature_idx = [i for i, m in enumerate(self.mappers)
                                     if not m.is_trivial]
            return
        self._find_mappers(X, config, categorical, forced_bins,
                           total_rows=total_rows)

    def _find_mappers(self, X: np.ndarray, config: Config,
                      categorical_features: Sequence[int],
                      forced_bins: Dict[int, List[float]],
                      total_rows: Optional[int] = None,
                      feature_subset: Optional[Sequence[int]] = None
                      ) -> None:
        # total_rows: full dataset size when X is already a sample (the
        # two-round path) — the near-unsplittable filter must scale by
        # sample/total like the reference (dataset_loader.cpp:599-600);
        # the internal subsample below still indexes X's own rows.
        # feature_subset: X's columns' GLOBAL feature ids (distributed
        # feature-sharded bin finding) — per-feature config (ignore,
        # max_bin_by_feature, categorical, forced bins) is keyed globally
        n, nf = X.shape
        full_n = max(int(total_rows), n) if total_rows is not None else n
        sample_cnt = min(n, int(config.bin_construct_sample_cnt))
        if sample_cnt < n:
            rng = np.random.default_rng(int(config.data_random_seed))
            sample_idx = np.sort(rng.choice(n, size=sample_cnt, replace=False))
            Xs = X[sample_idx]
        else:
            Xs = X
        # sparse input: per-column stored values come straight off the
        # CSC arrays — the f64 matrix is never densified (reference
        # sparse-aware sampling, dataset_loader.cpp:959-1042 /
        # src/io/sparse_bin.hpp:73)
        sp_csc = None
        if _is_scipy_sparse(Xs):
            sp_csc = Xs.tocsc()
            # duplicate coordinates sum under densification; match that
            # before reading stored values per column
            sp_csc.sum_duplicates()
        total = Xs.shape[0]

        ignore = set(_parse_column_spec(config.ignore_column, self.feature_names))
        cat_set = set(int(c) for c in categorical_features)
        max_bin_by_feature = list(config.max_bin_by_feature)
        # near-unsplittable feature filter (reference dataset_loader.cpp:599-600)
        filter_cnt = int(float(config.min_data_in_leaf) * total / full_n)

        self.mappers = [BinMapper() for _ in range(nf)]

        def _find_col(col: int) -> None:
            gcol = int(feature_subset[col]) if feature_subset is not None \
                else col
            m = self.mappers[col]
            if gcol in ignore:
                m.num_bin = 1
                m.is_trivial = True
                return
            if sp_csc is not None:
                colv = sp_csc.data[sp_csc.indptr[col]:sp_csc.indptr[col + 1]]
                colv = np.asarray(colv, dtype=np.float64)
            else:
                colv = Xs[:, col]
            # drop (near-)zeros: implied by total_sample_cnt (reference
            # dataset_loader.cpp sparse-aware sampling; stored sparse
            # zeros drop identically to dense explicit zeros)
            nonzero = colv[~((np.abs(colv) <= K_ZERO_THRESHOLD)
                             & ~np.isnan(colv))]
            mb = int(config.max_bin)
            if max_bin_by_feature and gcol < len(max_bin_by_feature):
                mb = int(max_bin_by_feature[gcol])
            m.find_bin(nonzero, total, mb,
                       min_data_in_bin=int(config.min_data_in_bin),
                       min_split_data=filter_cnt,
                       bin_type=(BinType.CATEGORICAL if gcol in cat_set
                                 else BinType.NUMERICAL),
                       use_missing=bool(config.use_missing),
                       zero_as_missing=bool(config.zero_as_missing),
                       forced_bounds=forced_bins.get(gcol))

        # per-column fan-out (reference OpenMP pragma over features,
        # dataset_loader.cpp:696): each column fills only its own
        # pre-constructed mapper, so the result is order-independent
        _parallel_columns(_find_col, nf, config)
        self.used_feature_idx = [c for c in range(nf)
                                 if not self.mappers[c].is_trivial]

    def _set_constraints(self, config: Config) -> None:
        mono = list(config.monotone_constraints)
        if mono:
            self.monotone_constraints = np.array(
                [mono[c] if c < len(mono) else 0 for c in self.used_feature_idx],
                dtype=np.int32)
        contri = list(config.feature_contri)
        if contri:
            self.feature_penalty = np.array(
                [contri[c] if c < len(contri) else 1.0 for c in self.used_feature_idx],
                dtype=np.float32)

    # ------------------------------------------------------------------
    def create_valid(self, X, label: Optional[np.ndarray] = None,
                     **kw) -> "TrainingData":
        factory = (TrainingData.from_sparse if _is_scipy_sparse(X)
                   else TrainingData.from_matrix)
        return factory(X, label, self.config, reference=self, **kw)

    def real_threshold(self, feature: int, bin_threshold: int) -> float:
        """Bin threshold -> raw-value threshold for model serialization.

        Numerical split at bin t means `value <= bin_upper_bound[t]` goes left
        (reference Tree::RealThreshold usage in tree.cpp).
        """
        m = self.mappers[self.used_feature_idx[feature]]
        return m.bin_to_value(bin_threshold)
