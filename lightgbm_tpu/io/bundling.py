"""EFB — exclusive feature bundling.

Plays the role of the reference's `FindGroups` / `FastFeatureBundling`
(reference src/io/dataset.cpp:91-263) + `FeatureGroup` storage (reference
include/LightGBM/feature_group.h:37-53): (almost-)mutually-exclusive
sparse features share one bundle column, shrinking the histogram matrix's
feature axis — on TPU that directly shrinks the one-hot contraction's
F*B dimension, so it is a compute win as well as a memory win.

Scheme (simplified relative to the reference, same math contract):
* only features whose MOST FREQUENT bin is bin 0 are bundling candidates
  (the sparse/one-hot case the reference optimizes; dense features keep
  their own column);
* greedy first-fit by descending nonzero count, with a per-bundle
  conflict budget of max_conflict_rate * n rows (reference
  dataset.cpp:115-157) and a bin-capacity cap;
* bundle column value: 0 when every member is at bin 0, else
  offset_i + bin (bins 1..num_bin_i-1 of member i map to
  [offset_i+1, offset_i+num_bin_i-1]); on a (budgeted) conflict the
  later member wins, like the reference's sequential push;
* the per-feature bin-0 row is NOT recoverable from the bundle column —
  the grower reconstructs it per leaf as total - sum(other bins), the
  analog of Dataset::FixHistogram (reference src/io/dataset.cpp:
  1044-1063).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np


# default row-sample quota the bundling greedy counts conflicts on; the
# learner pre-samples through TrainingData.strided_row_sample with the
# SAME constant so device-resident matrices never materialize wholesale
EFB_SAMPLE_ROWS = 100_000


class BundlePlan(NamedTuple):
    # per bundle: list of used-feature positions (len 1 = untouched column)
    groups: List[List[int]]
    # per used feature: bundle index and bin offset within it
    bundle_idx: np.ndarray      # [F] int32
    bin_offset: np.ndarray      # [F] int32 (0 for singleton columns)
    needs_fix: np.ndarray       # [F] bool: bin 0 must be reconstructed
    num_bin: np.ndarray         # [G] int32 bins per bundle column

    @property
    def num_columns(self) -> int:
        return len(self.groups)

    @property
    def is_trivial(self) -> bool:
        return all(len(g) == 1 for g in self.groups)


def _stride_sample(bins: np.ndarray, quota: int) -> np.ndarray:
    """Deterministic strided row sample, shared by the local and
    multihost finders so their plan-parity holds."""
    n = bins.shape[0]
    if n > quota:
        step = n // quota
        return bins[::step][:quota]
    return bins


def find_bundles(bins: np.ndarray, num_bin: np.ndarray,
                 most_freq_is_zero: np.ndarray, max_conflict_rate: float,
                 max_bundle_bins: int, sample_rows: int = EFB_SAMPLE_ROWS
                 ) -> BundlePlan:
    """Greedy conflict-budget bundling over the binned [n, F] matrix.

    num_bin / most_freq_is_zero are per used feature; conflicts are
    counted on a row sample like the reference's sampled FindGroups.
    """
    n, F = bins.shape
    sample = _stride_sample(bins, sample_rows)
    ns = sample.shape[0]
    budget_total = max_conflict_rate * ns

    nz = sample != 0                      # [ns, F] non-default mask
    nz_count = nz.sum(axis=0)
    candidates = [f for f in range(F)
                  if most_freq_is_zero[f] and num_bin[f] <= max_bundle_bins]
    # densest first so heavy features anchor bundles (reference sorts by
    # conflict count, dataset.cpp:133)
    candidates.sort(key=lambda f: -int(nz_count[f]))

    groups: List[List[int]] = []
    occupied: List[np.ndarray] = []       # [ns] bool per bundle
    conflicts: List[int] = []
    bin_used: List[int] = []
    for f in candidates:
        placed = False
        for gi in range(len(groups)):
            if bin_used[gi] + int(num_bin[f]) - 1 > max_bundle_bins - 1:
                continue
            c = int((nz[:, f] & occupied[gi]).sum())
            if conflicts[gi] + c <= budget_total:
                groups[gi].append(f)
                occupied[gi] |= nz[:, f]
                conflicts[gi] += c
                bin_used[gi] += int(num_bin[f]) - 1
                placed = True
                break
        if not placed:
            groups.append([f])
            occupied.append(nz[:, f].copy())
            conflicts.append(0)
            bin_used.append(int(num_bin[f]) - 1)

    # drop singleton "bundles" back into plain columns; order: real
    # bundles first, then untouched features in original order
    real = [g for g in groups if len(g) > 1]
    bundled_feats = {f for g in real for f in g}
    final: List[List[int]] = real + [[f] for f in range(F)
                                     if f not in bundled_feats]

    bundle_idx = np.zeros(F, np.int32)
    bin_offset = np.zeros(F, np.int32)
    needs_fix = np.zeros(F, bool)
    g_bins = np.zeros(len(final), np.int32)
    for gi, g in enumerate(final):
        if len(g) == 1:
            f = g[0]
            bundle_idx[f] = gi
            bin_offset[f] = 0
            g_bins[gi] = num_bin[f]
            continue
        off = 0
        for f in g:
            bundle_idx[f] = gi
            bin_offset[f] = off
            needs_fix[f] = True
            off += int(num_bin[f]) - 1
        g_bins[gi] = off + 1
    return BundlePlan(groups=final, bundle_idx=bundle_idx,
                      bin_offset=bin_offset, needs_fix=needs_fix,
                      num_bin=g_bins)


def find_bundles_multihost(local_bins: np.ndarray, num_bin: np.ndarray,
                           local_zero_frac: np.ndarray, local_rows: int,
                           sparse_threshold: float,
                           max_conflict_rate: float,
                           max_bundle_bins: int,
                           sample_rows: int = EFB_SAMPLE_ROWS) -> BundlePlan:
    """Bundling plan agreed across a jax.distributed process group.

    EVERYTHING plan-determining reduces globally inside this function —
    callers pass only LOCAL statistics (zero fractions and row count
    from this rank's rows), so no half of the agreement contract can be
    forgotten at a call site.  The candidate filter comes from the
    globally weighted zero fractions; the greedy's per-bundle occupancy
    is a UNION over sample rows, so a consistent plan cannot come from
    locally-found plans or pairwise count sums: every rank contributes
    an equal quota of its local rows, the samples allgather (ragged,
    integer transport — never demoted; uint16 normally, widened to
    uint32 when any feature's bin ids exceed the uint16 range so the
    gather cannot silently truncate them), and the IDENTICAL greedy
    runs on the identical global sample everywhere.  Single-process
    groups degrade to the local find.
    """
    import jax

    nproc = jax.process_count()
    if nproc <= 1:
        return find_bundles(local_bins, num_bin,
                            local_zero_frac >= sparse_threshold,
                            max_conflict_rate, max_bundle_bins,
                            sample_rows=sample_rows)
    from ..parallel.topology import host_allgather, ragged_all_gather

    # globally weighted zero fractions decide the candidate set; both
    # exchanges ride distributed bin finding's own fault point so chaos
    # runs can target ingest separately from train-loop sync
    zf = host_allgather(
        np.concatenate([np.asarray(local_zero_frac, np.float64)
                        * local_rows, [local_rows]]).astype(np.float32),
        name="efb_zero_frac", point="binning_allgather")
    tot = zf.sum(axis=0)
    mfz = tot[:-1] / max(tot[-1], 1) >= sparse_threshold
    samp = _stride_sample(local_bins, max(1, sample_rows // nproc))
    # transport dtype must hold every bin id: uint16 truncates silently
    # past 65535, so wide-bin features ride uint32 instead (num_bin is
    # plan input on every rank, so all ranks agree on the widening)
    transport = (np.uint32
                 if int(np.asarray(num_bin).max(initial=0))
                 > int(np.iinfo(np.uint16).max)
                 else np.uint16)
    sample_global = ragged_all_gather(np.ascontiguousarray(
        samp, dtype=transport), name="efb_bundle_exchange",
        point="binning_allgather")
    return find_bundles(sample_global, num_bin, mfz,
                        max_conflict_rate, max_bundle_bins,
                        sample_rows=sample_global.shape[0])


def apply_bundles(bins: np.ndarray, plan: BundlePlan) -> np.ndarray:
    """[n, F] feature bins -> [n, G] bundle columns."""
    n = bins.shape[0]
    out = np.zeros((n, plan.num_columns), dtype=np.int32)
    for gi, g in enumerate(plan.groups):
        if len(g) == 1:
            out[:, gi] = bins[:, g[0]]
            continue
        col = np.zeros(n, np.int32)
        for f in g:
            b = bins[:, f].astype(np.int32)
            nzr = b != 0
            # later members overwrite on (budgeted) conflict rows
            col[nzr] = b[nzr] + plan.bin_offset[f]
        out[:, gi] = col
    return out
