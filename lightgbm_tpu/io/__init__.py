from .bin_mapper import BinMapper, MissingType, BinType
from .dataset import TrainingData, Metadata
from .parser import load_text_file
