"""Text data loading: CSV / TSV / LibSVM with autodetection.

Mirrors the reference parser behavior (reference src/io/parser.cpp:222 and
src/io/dataset_loader.cpp:168-330): delimiter + format autodetect from the
first lines, optional header, label column by index or `name:<col>`, and
side-car `.weight` / `.query` / `.init` files next to the data file.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np


def _detect_format(first_lines: List[str]) -> Tuple[str, str]:
    """Return (kind, delimiter) with kind in {'libsvm','csv','tsv','space'}."""
    for line in first_lines:
        toks = line.strip().split()
        if len(toks) >= 2 and ":" in toks[1]:
            parts = toks[1].split(":")
            if len(parts) == 2:
                try:
                    int(parts[0]); float(parts[1])
                    return "libsvm", " "
                except ValueError:
                    pass
        if "\t" in line:
            return "tsv", "\t"
        if "," in line:
            return "csv", ","
    return "space", " "


def _has_header(line: str, delim: str) -> bool:
    toks = [t for t in line.strip().split(delim) if t != ""]
    for t in toks:
        try:
            float(t)
            return False
        except ValueError:
            continue
    return len(toks) > 0


def load_text_file(path: str, label_column: str = "", header: Optional[bool] = None,
                   num_features_hint: int = 0
                   ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray],
                              Optional[np.ndarray], Optional[np.ndarray], List[str]]:
    """Load a training/prediction text file.

    Returns (X [n,F] float64 w/ NaN for missing, y [n], weight or None,
    group_sizes or None, init_score or None, feature_names).
    """
    with open(path) as f:
        head = []
        for _ in range(5):
            line = f.readline()
            if not line:
                break
            if line.strip():
                head.append(line)
    if not head:
        raise ValueError(f"empty data file {path}")
    kind, delim = _detect_format(head)

    label_idx = 0
    label_name = None
    if label_column:
        if str(label_column).startswith("name:"):
            label_name = str(label_column)[5:]
        elif str(label_column) != "":
            label_idx = int(label_column)

    feature_names: List[str] = []
    if kind == "libsvm":
        X, y = _load_libsvm(path, num_features_hint)
        feature_names = [f"Column_{i}" for i in range(X.shape[1])]
    else:
        import pandas as pd
        use_header = _has_header(head[0], delim) if header is None else header
        df = pd.read_csv(path, sep=delim, header=0 if use_header else None,
                         na_values=["", "NA", "N/A", "nan", "NaN", "null"])
        if use_header:
            cols = [str(c) for c in df.columns]
            if label_name is not None:
                label_idx = cols.index(label_name)
            feature_names = [c for i, c in enumerate(cols) if i != label_idx]
        else:
            feature_names = [f"Column_{i}" for i in range(df.shape[1] - 1)]
        arr = df.to_numpy(dtype=np.float64)
        y = arr[:, label_idx].copy()
        X = np.delete(arr, label_idx, axis=1)

    weight, group_arr, init_score = load_sidecars(path)
    return X, y, weight, group_arr, init_score, feature_names


def load_sidecars(path):
    """(weight, group_sizes int64 or None, init_score) side-car files
    next to the data file (reference dataset_loader.cpp metadata files)."""
    weight = _load_sidecar(path + ".weight")
    group = _load_sidecar(path + ".query")
    if group is None:
        group = _load_sidecar(path + ".group")
    init_score = _load_sidecar(path + ".init")
    group_arr = group.astype(np.int64) if group is not None else None
    return weight, group_arr, init_score


def _load_sidecar(path: str) -> Optional[np.ndarray]:
    if not os.path.exists(path):
        return None
    vals = np.loadtxt(path, dtype=np.float64, ndmin=1)
    return vals


def _load_libsvm(path: str, num_features_hint: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            labels.append(float(toks[0]))
            row: Dict[int, float] = {}
            for tok in toks[1:]:
                k, v = tok.split(":")
                idx = int(k)
                row[idx] = float(v)
                max_idx = max(max_idx, idx)
            rows.append(row)
    nf = max(max_idx + 1, num_features_hint)
    X = np.zeros((len(rows), nf), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float64)


class TextChunkReader:
    """Streaming chunk reader for CSV/TSV/space files (two-round loading).

    The reference's two_round path never holds the raw matrix: one pass
    samples rows for bin finding, a second streams rows straight into bins
    (reference src/io/dataset_loader.cpp:188-216).  LibSVM files fall back
    to one-pass loading (load_text_file) — the sparse format is small on
    disk by construction.
    """

    def __init__(self, path: str, label_column: str = "",
                 header: Optional[bool] = None, chunk_rows: int = 200_000):
        self.path = path
        self.chunk_rows = int(chunk_rows)
        with open(path) as f:
            head = []
            for _ in range(5):
                line = f.readline()
                if not line:
                    break
                if line.strip():
                    head.append(line)
        if not head:
            raise ValueError(f"empty data file {path}")
        self.kind, self.delim = _detect_format(head)
        if self.kind == "libsvm":
            raise ValueError("TextChunkReader does not stream libsvm")
        self.use_header = (_has_header(head[0], self.delim)
                           if header is None else header)
        self.label_idx = 0
        label_name = None
        if label_column:
            if str(label_column).startswith("name:"):
                label_name = str(label_column)[5:]
            elif str(label_column) != "":
                self.label_idx = int(label_column)
        if self.use_header:
            # pandas-parsed names (quoting/padding aware) so the streaming
            # path resolves label names exactly like load_text_file
            import pandas as pd

            cols = [str(c) for c in pd.read_csv(
                path, sep=self.delim, nrows=0).columns]
            if label_name is not None:
                self.label_idx = cols.index(label_name)
            self.feature_names = [c for i, c in enumerate(cols)
                                  if i != self.label_idx]
        else:
            ncol = len([t for t in head[0].strip().split(self.delim)
                        if t != ""])
            self.feature_names = [f"Column_{i}" for i in range(ncol - 1)]

    def chunks(self):
        """Yield (X_chunk [m,F] f64, y_chunk [m]) in file order."""
        import pandas as pd

        reader = pd.read_csv(
            self.path, sep=self.delim,
            header=0 if self.use_header else None,
            na_values=["", "NA", "N/A", "nan", "NaN", "null"],
            chunksize=self.chunk_rows)
        for df in reader:
            arr = df.to_numpy(dtype=np.float64)
            y = arr[:, self.label_idx].copy()
            X = np.delete(arr, self.label_idx, axis=1)
            yield X, y
