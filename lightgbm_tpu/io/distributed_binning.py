"""Multi-host bin finding: feature-sharded mapper search + allgather.

The reference's distributed loader pre-partitions rows across machines and
splits BIN FINDING by feature: each machine runs FindBin for its assigned
feature range on its LOCAL sample, then `Network::Allgather` exchanges the
serialized BinMappers so every machine ends with the full mapper set
(reference src/io/dataset_loader.cpp:959-1042).  Bins are therefore found
from partial (per-machine) data by design — machines see different rows,
and the global mapper for feature f is whichever machine owned f.

TPU-native equivalent: hosts in a `jax.distributed` run exchange mapper
dicts via the topology layer's ragged allgather on a JSON payload.  The
assignment and merge are pure functions so single-process tests can
exercise them without a multi-host runtime.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from .bin_mapper import BinMapper


def ensure_distributed(config: Config) -> None:
    """Bootstrap the jax.distributed rendezvous when the config asks for
    a machine group.  A pre-partitioned Dataset is often the FIRST jax
    touch in the process (constructed before any learner); the
    rendezvous must run before anything initializes the backend, or
    jax.distributed.initialize becomes impossible for the whole process.
    Explicitly a SIDE-EFFECTING entry-point call (it can block on peers
    or raise on an unresolvable machine list) — the
    config_wants_distributed predicate below stays pure.
    init_multihost is idempotent."""
    if (bool(config.pre_partition) and str(config.machines)
            and int(config.num_machines) > 1):
        from ..parallel.collective import configure_from_config
        from ..parallel.mesh import init_multihost

        # the rendezvous is the FIRST collective: arm the process-wide
        # watchdog defaults before it (Network::Init ordering)
        configure_from_config(config)
        init_multihost(str(config.machines),
                       int(config.local_listen_port),
                       int(config.num_machines))


def config_wants_distributed(config: Config) -> bool:
    """Single predicate for every site that must agree on whether this
    process joins the collective bin-finding path — the cache-skip in
    from_file and the routing in _find_mappers_maybe_distributed must
    never diverge, or one host deadlocks the group's allgather."""
    if not bool(config.pre_partition):
        return False
    import jax

    return jax.process_count() > 1


def assign_features(num_features: int, num_machines: int) -> List[List[int]]:
    """Contiguous per-machine feature ranges, balanced by count (the
    reference balances by bin count after a first pass; contiguous ranges
    keep the allgather order deterministic)."""
    base = num_features // num_machines
    extra = num_features % num_machines
    out: List[List[int]] = []
    start = 0
    for m in range(num_machines):
        width = base + (1 if m < extra else 0)
        out.append(list(range(start, start + width)))
        start += width
    return out


def merge_mapper_payloads(payloads: Sequence[str],
                          num_features: int) -> List[BinMapper]:
    """Allgathered JSON payloads -> full mapper list.

    Each payload is `{"features": [...], "mappers": [dict, ...]}` from one
    machine; every feature must be covered exactly once.
    """
    mappers: List[Optional[BinMapper]] = [None] * num_features
    for payload in payloads:
        obj = json.loads(payload)
        for f, md in zip(obj["features"], obj["mappers"]):
            if mappers[f] is not None:
                raise ValueError(f"feature {f} assigned to two machines")
            mappers[f] = BinMapper.from_dict(md)
    missing = [f for f, m in enumerate(mappers) if m is None]
    if missing:
        raise ValueError(f"features {missing[:5]}... missing from allgather")
    return mappers  # type: ignore[return-value]


def local_payload(X_local: np.ndarray, features: Sequence[int],
                  config: Config, categorical: Sequence[int] = (),
                  forced_bins: Optional[Dict[int, List[float]]] = None,
                  total_rows: Optional[int] = None,
                  feature_names: Optional[Sequence[str]] = None) -> str:
    """Find this machine's assigned features' mappers on its local rows.

    Per-feature config (ignore_column, max_bin_by_feature, categorical,
    forced bins) stays keyed by GLOBAL feature id via feature_subset;
    feature_names must be the dataset's REAL names so name-based
    ignore_column specs resolve identically on every host."""
    from .dataset import TrainingData

    td = TrainingData()
    td.feature_names = (list(feature_names) if feature_names is not None
                        else [f"Column_{i}"
                              for i in range(X_local.shape[1])])
    td._find_mappers(X_local[:, list(features)], config,
                     list(categorical), dict(forced_bins or {}),
                     total_rows=total_rows,
                     feature_subset=list(features))
    return json.dumps({
        "features": list(features),
        "mappers": [m.to_dict() for m in td.mappers]})


def gather_row_samples(X_local: np.ndarray, quota: int,
                       seed: int) -> np.ndarray:
    """Deterministic per-host row sample, allgathered into ONE global
    bin-finding sample every host holds identically.

    The ragged transport (per-host lengths allgather, zero-padded
    payload block, per-host slices back out in process order) is
    `topology.ragged_all_gather` — ONE logical collective under ONE
    watchdog, on binning's own fault point so chaos runs can target
    ingest separately from train-loop sync.  The result is
    deterministic given (data, seed, process layout).  Each host
    contributes at most `quota` of its local rows (sorted deterministic
    choice, the same sampler `_find_mappers` uses)."""
    from ..parallel.topology import ragged_all_gather

    n = X_local.shape[0]
    if n > quota:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, size=quota, replace=False))
        samp = np.ascontiguousarray(
            np.asarray(X_local, np.float64)[idx])
    else:
        samp = np.asarray(X_local, np.float64)
    return ragged_all_gather(samp, name="gather_row_samples",
                             point="binning_allgather")


def find_mappers_multihost(X_local: np.ndarray, config: Config,
                           categorical: Sequence[int] = (),
                           forced_bins: Optional[Dict[int, List[float]]]
                           = None,
                           local_total_rows: Optional[int] = None,
                           feature_names: Optional[Sequence[str]] = None
                           ) -> List[BinMapper]:
    """Distributed bin finding across the jax.distributed process group.

    Single-process runs degrade to a plain local find over all features.
    local_total_rows is THIS host's full row count when X_local is already
    a sample (two-round); the near-unsplittable filter always scales
    against the allgather-summed GLOBAL count.

    Dense inputs first gather a `bin_construct_sample_cnt`-bounded
    GLOBAL row sample (each host contributes an equal quota of its local
    rows), so feature f's mapper no longer depends on which host owned f
    — boundaries are consistent with what a single-host find over the
    same sample would produce.  Sparse inputs keep the reference's
    local-rows behavior (densifying a wide sparse sample for transport
    would defeat the O(nnz) ingest path).
    """
    import jax

    nproc = jax.process_count()
    nf = X_local.shape[1]
    if nproc <= 1:
        payload = local_payload(X_local, list(range(nf)), config,
                                categorical, forced_bins,
                                total_rows=local_total_rows,
                                feature_names=feature_names)
        return merge_mapper_payloads([payload], nf)
    from ..parallel.topology import host_allgather, ragged_all_gather

    local_n = int(local_total_rows if local_total_rows is not None
                  else X_local.shape[0])
    global_rows = int(host_allgather(
        np.asarray([local_n], np.int64),
        name="global_row_count", point="binning_allgather").sum())
    assignment = assign_features(nf, nproc)
    mine = assignment[jax.process_index()]
    from .dataset import _is_scipy_sparse

    X_find = X_local
    if not _is_scipy_sparse(X_local):
        quota = max(1, int(config.bin_construct_sample_cnt) // nproc)
        X_find = gather_row_samples(np.asarray(X_local, np.float64),
                                    quota, int(config.data_random_seed))
    payload = local_payload(X_find, mine, config, categorical, forced_bins,
                            total_rows=global_rows,
                            feature_names=feature_names)

    # ragged byte transport, split back per host so each serialized
    # payload decodes at its own boundary
    raw = np.frombuffer(payload.encode(), np.uint8)
    parts = ragged_all_gather(raw, name="mapper_exchange",
                              point="binning_allgather", split=True)
    payloads = [bytes(p).decode() for p in parts]
    return merge_mapper_payloads(payloads, nf)
