"""Per-feature value -> bin quantization.

Behavioral re-implementation of the reference BinMapper
(reference src/io/bin.cpp:78-470, include/LightGBM/bin.h:65-230):

* numerical features: greedy equal-count bin boundary search
  (`GreedyFindBin`, bin.cpp:78) with the zero-as-one-bin variant
  (`FindBinWithZeroAsOneBin`, bin.cpp:256) that dedicates one bin to
  [-1e-35, 1e-35] and splits the budget between negative / positive values;
* categorical features: categories sorted by count, mapped to bins until 99%
  coverage, rare categories -> the NaN bin (bin.cpp:410-460);
* missing handling: None / Zero / NaN (bin.h:26-30) — with MissingType.NaN the
  last bin is reserved for NaN values;
* forced bin bounds (`forcedbins_filename`, bin.cpp:157-255).

Bin semantics: numerical bin `i` holds values v with
`bin_upper_bound[i-1] < v <= bin_upper_bound[i]`; the last real upper bound is
+inf.  `value_to_bin` therefore is a searchsorted over the upper bounds
(reference `BinMapper::ValueToBin`, bin.h:472-508).
"""

from __future__ import annotations

import enum
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

K_ZERO_THRESHOLD = 1e-35  # reference include/LightGBM/meta.h:53
_F32_INF = float("inf")
_NO_IDX = 1 << 60  # "no candidate" sentinel for the vectorized greedy


class MissingType(enum.IntEnum):
    NONE = 0
    ZERO = 1
    NAN = 2


class BinType(enum.IntEnum):
    NUMERICAL = 0
    CATEGORICAL = 1


def sort_keys(values: np.ndarray) -> np.ndarray:
    """f64 -> monotone int64 keys; NaN -> INT64_MAX sentinel.

    key(x) = bits(x) for bits >= 0 else INT64_MIN - bits(x): a total
    order identical to the f64 '<' order, with -0.0 and +0.0 keying
    equal (both 0).  Shared by the host fast binning path below and the
    ops/binning.py device kernel (integer compares are exact on every
    backend, unlike f32-demoted float compares).
    """
    v = np.ascontiguousarray(values, dtype=np.float64)
    bits = v.view(np.int64)
    keys = np.where(bits >= 0, bits,
                    np.int64(np.iinfo(np.int64).min) - bits)
    return np.where(np.isnan(v), np.int64(np.iinfo(np.int64).max), keys)


def _upper_bound(a: float) -> float:
    """Smallest double strictly greater than a (reference Common::GetDoubleUpperBound)."""
    return float(np.nextafter(a, np.inf))


def _equal_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a, inf) (reference Common::CheckDoubleEqualOrdered)."""
    return b <= np.nextafter(a, np.inf)


def greedy_find_bin_scalar(distinct_values: Sequence[float],
                           counts: Sequence[int], max_bin: int,
                           total_cnt: int,
                           min_data_in_bin: int) -> List[float]:
    """Greedy equal-count boundary search (reference src/io/bin.cpp:78-155).

    Returns bin upper bounds; the last is +inf.

    This is the straight per-value transcription of the reference loop —
    O(num_distinct) Python iterations.  It is kept as the parity oracle
    for the vectorized `greedy_find_bin` below, which must produce
    bit-identical boundaries (tests/test_ingest.py).
    """
    assert max_bin > 0
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _equal_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur_cnt_inbin = 0
        bounds.append(_F32_INF)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    # values with count >= mean size get their own bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = [False] * num_distinct
    for i in range(num_distinct):
        if counts[i] >= mean_bin_size:
            is_big[i] = True
            rest_bin_cnt -= 1
            rest_sample_cnt -= counts[i]
    # C++ float semantics: x/0 is inf (every distinct value "big" leaves
    # rest_bin_cnt == 0, reference bin.cpp:116 tolerates it); Python's /
    # would raise instead
    with np.errstate(divide="ignore", invalid="ignore"):
        mean_bin_size = float(np.float64(rest_sample_cnt)
                              / np.float64(rest_bin_cnt))

    uppers = [_F32_INF] * max_bin
    lowers = [_F32_INF] * max_bin
    bin_cnt = 0
    lowers[0] = distinct_values[0]
    cur_cnt_inbin = 0
    # 0.5f: the reference multiplies by a float literal (bin.cpp:131)
    half = np.float32(0.5)
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt_inbin += counts[i]
        if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * half))):
            uppers[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lowers[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                with np.errstate(divide="ignore", invalid="ignore"):
                    mean_bin_size = float(np.float64(rest_sample_cnt)
                                          / np.float64(rest_bin_cnt))
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _upper_bound((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _equal_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(_F32_INF)
    return bounds


def _ceil_int(x) -> int:
    """Smallest integer >= x, exact for any finite float.

    For integer d and float threshold t, `d >= t` (the scalar loop's
    closure test, exact because ints below 2**53 convert to f64
    losslessly) is equivalent to `d >= ceil(t)` — which turns the
    running-count comparison into an integer searchsorted key."""
    return math.ceil(float(x))


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """Vectorized greedy equal-count boundary search.

    Bit-identical to `greedy_find_bin_scalar` (the reference
    bin.cpp:78-155 transcription) but O(max_bin * log n) instead of
    O(num_distinct) Python iterations: the closure condition
    `cur_cnt_inbin >= threshold` is a searchsorted over the exact
    integer cumulative counts (thresholds via `_ceil_int`), and the
    is_big interrupts come from precomputed sorted index arrays.  The
    running `mean_bin_size` re-division only happens when a bin closes,
    so the state machine advances one CLOSURE per step, not one value.
    """
    assert max_bin > 0
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnt = np.asarray(counts, dtype=np.int64)
    num_distinct = len(dv)
    bounds: List[float] = []
    cum = np.cumsum(cnt) if num_distinct else np.zeros(0, np.int64)

    if num_distinct <= max_bin:
        # closure at the first i with cum-from-start >= min_data_in_bin;
        # a deduped (rejected) boundary keeps accumulating, so the next
        # candidate is simply i+1 (the condition stays satisfied)
        base = 0
        pos = 0
        last = num_distinct - 1  # i ranges over [0, num_distinct-2]
        while pos < last:
            j = int(np.searchsorted(cum[:last], base + min_data_in_bin,
                                    side="left"))
            j = max(j, pos)
            if j >= last:
                break
            val = _upper_bound((dv[j] + dv[j + 1]) / 2.0)
            if not bounds or not _equal_ordered(bounds[-1], val):
                bounds.append(val)
                base = int(cum[j])
            pos = j + 1
        bounds.append(_F32_INF)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    is_big = cnt >= mean_bin_size  # exact: int64 -> f64 lossless here
    rest_bin_cnt = int(max_bin - is_big.sum())
    rest_sample0 = int(total_cnt - cnt[is_big].sum())
    with np.errstate(divide="ignore", invalid="ignore"):
        mean_bin_size = float(np.float64(rest_sample0)
                              / np.float64(rest_bin_cnt))

    big_idx = np.flatnonzero(is_big)
    # positions i (<= nd-2) whose SUCCESSOR is big — the half-mean early
    # closure sites; their cum values stay sorted for searchsorted
    b3_idx = np.flatnonzero(is_big[1:])
    b3_cum = cum[b3_idx]
    nb_cum = np.cumsum(np.where(is_big, 0, cnt))

    uppers = np.full(max_bin + 1, _F32_INF)
    lowers = np.full(max_bin + 1, _F32_INF)
    bin_cnt = 0
    lowers[0] = dv[0]
    half = np.float32(0.5)
    start = 0
    last = num_distinct - 1  # loop domain is [0, num_distinct-2]
    while start < last:
        base = int(cum[start - 1]) if start > 0 else 0
        # c1: next value that is itself big
        p = int(np.searchsorted(big_idx, start))
        c1 = int(big_idx[p]) if p < len(big_idx) else _NO_IDX
        if c1 >= last:
            c1 = _NO_IDX
        # c2: running count reaches mean_bin_size
        c2 = _NO_IDX
        if math.isfinite(mean_bin_size):
            j = int(np.searchsorted(cum[:last],
                                    base + _ceil_int(mean_bin_size),
                                    side="left"))
            c2 = max(j, start) if j < last else _NO_IDX
        # c3: successor is big and running count reaches half the mean
        c3 = _NO_IDX
        if len(b3_idx):
            q = int(np.searchsorted(b3_idx, start))
            if q < len(b3_idx):
                thr3 = max(1.0, mean_bin_size * half)
                if math.isfinite(thr3):
                    r = q + int(np.searchsorted(b3_cum[q:],
                                                base + _ceil_int(thr3),
                                                side="left"))
                    if r < len(b3_idx):
                        c3 = max(int(b3_idx[r]), start)
        i = min(c1, c2, c3)
        if i >= last:
            break
        uppers[bin_cnt] = dv[i]
        bin_cnt += 1
        lowers[bin_cnt] = dv[i + 1]
        if bin_cnt >= max_bin - 1:
            break
        if not is_big[i]:
            rest_bin_cnt -= 1
            with np.errstate(divide="ignore", invalid="ignore"):
                mean_bin_size = float(
                    np.float64(rest_sample0 - int(nb_cum[i]))
                    / np.float64(rest_bin_cnt))
        start = i + 1
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _upper_bound((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _equal_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(_F32_INF)
    return bounds


def _find_bin_zero_as_one(distinct_values: Sequence[float], counts: Sequence[int],
                          max_bin: int, total_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """Zero-as-one-bin boundary search (reference src/io/bin.cpp:256-313).

    The left/zero/right partition is a pair of searchsorteds over the
    sorted distinct values instead of a per-value scan."""
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnt = np.asarray(counts, dtype=np.int64)
    num_distinct = len(dv)
    cum = np.concatenate([[0], np.cumsum(cnt)])
    # first index with v > -K / v > K (side='right' == strict >)
    left_cnt = int(np.searchsorted(dv, -K_ZERO_THRESHOLD, side="right"))
    rs = int(np.searchsorted(dv, K_ZERO_THRESHOLD, side="right"))
    left_cnt_data = int(cum[left_cnt])
    cnt_zero = int(cum[rs] - cum[left_cnt])
    right_cnt_data = int(cum[num_distinct] - cum[rs])

    bounds: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        left_max_bin = max(
            1, int(left_cnt_data / max(1, total_cnt - cnt_zero) * (max_bin - 1)))
        bounds = greedy_find_bin(dv[:left_cnt], cnt[:left_cnt],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    right_start = rs if rs < num_distinct else -1

    right_max_bin = max_bin - 1 - len(bounds)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(dv[right_start:],
                                       cnt[right_start:], right_max_bin,
                                       right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(_F32_INF)
    assert len(bounds) <= max_bin
    return bounds


def _find_bin_with_forced(distinct_values: Sequence[float], counts: Sequence[int],
                          max_bin: int, total_cnt: int, min_data_in_bin: int,
                          forced_bounds: Sequence[float]) -> List[float]:
    """Forced-boundary variant (reference src/io/bin.cpp:157-255)."""
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnt = np.asarray(counts, dtype=np.int64)
    num_distinct = len(dv)
    cum = np.concatenate([[0], np.cumsum(cnt)])
    left_cnt = int(np.searchsorted(dv, -K_ZERO_THRESHOLD, side="right"))
    rs = int(np.searchsorted(dv, K_ZERO_THRESHOLD, side="right"))
    right_start = rs if rs < num_distinct else -1

    bounds: List[float] = []
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bounds.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(_F32_INF)

    max_to_insert = max_bin - len(bounds)
    num_inserted = 0
    for b in forced_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bounds.append(float(b))
            num_inserted += 1
    bounds.sort()

    free_bins = max_bin - len(bounds)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_bounds = len(bounds)
    for i in range(n_bounds):
        bin_start = value_ind
        # first distinct value >= bounds[i] ends this segment (the
        # per-value advance walk, as one searchsorted)
        value_ind = int(np.searchsorted(dv, bounds[i], side="left"))
        cnt_in_bin = int(cum[value_ind] - cum[bin_start])
        bins_remaining = max_bin - n_bounds - len(bounds_to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / max(1, total_cnt)))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_bounds - 1:
            num_sub_bins = bins_remaining + 1
        new_bounds = greedy_find_bin(dv[bin_start:value_ind],
                                     cnt[bin_start:value_ind],
                                     num_sub_bins, cnt_in_bin, min_data_in_bin)
        bounds_to_add.extend(new_bounds[:-1])  # last is +inf
    bounds.extend(bounds_to_add)
    bounds.sort()
    assert len(bounds) <= max_bin
    return bounds


class BinMapper:
    """Quantizer for one feature (reference include/LightGBM/bin.h:65-230)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.is_trivial: bool = True
        self.bin_type: BinType = BinType.NUMERICAL
        self.missing_type: MissingType = MissingType.NONE
        self.bin_upper_bound: np.ndarray = np.array([_F32_INF])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0      # bin of value 0.0
        self.most_freq_bin: int = 0
        self.sparse_rate: float = 0.0

    # ------------------------------------------------------------------
    def find_bin(self, sample_values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: BinType = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False,
                 forced_bounds: Optional[Sequence[float]] = None) -> None:
        """Compute bin boundaries from sampled non-zero values.

        `sample_values` excludes (near-)zero values; zeros are implied by
        `total_sample_cnt - len(sample_values)` as in the reference
        (src/io/bin.cpp:325-390).  NaNs may be present and are counted as
        missing.
        """
        values = np.asarray(sample_values, dtype=np.float64)
        na_cnt = int(np.isnan(values).sum())
        values = values[~np.isnan(values)]

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE
        if self.missing_type != MissingType.NAN:
            na_cnt = 0

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - values.size - na_cnt)

        # distinct values with zero spliced in at its sorted position.
        # Vectorized equal-ordered grouping (the scalar loop was the
        # binning hot spot at ~10s/1M rows): consecutive values with
        # next <= nextafter(prev, inf) merge, keeping the LARGER value —
        # i.e. each group's last element — exactly like the sequential
        # merge (reference bin.cpp:332-352 semantics).
        # unstable sort on purpose: values carry no payload and equal
        # doubles are bit-identical, so stability is unobservable —
        # introsort is measurably faster at the 200k-sample scale
        values = np.sort(values)
        distinct_values = np.zeros(0, np.float64)
        counts = np.zeros(0, np.int64)
        if values.size:
            new_group = values[1:] > np.nextafter(values[:-1], np.inf)
            last_idx = np.flatnonzero(np.append(new_group, True))
            dv = values[last_idx]
            cn = np.diff(np.concatenate([[-1], last_idx]))
            # splice zero (its count is implied, never sampled) at its
            # ordered position; sampled values are never exactly 0.0 (the
            # caller filtered |v| <= kZeroThreshold), so the insertion
            # point is unambiguous.  An INTERIOR zero (negatives and
            # positives both present) is inserted even at count 0 — the
            # scalar loop and reference bin.cpp:341-344 do, and the extra
            # zero-count entry changes categorical bin assembly
            if dv.size:
                pos = int(np.searchsorted(dv, 0.0))
                if zero_cnt > 0 or 0 < pos < len(dv):
                    dv = np.insert(dv, pos, 0.0)
                    cn = np.insert(cn, pos, zero_cnt)
            distinct_values = np.asarray(dv, np.float64)
            counts = cn.astype(np.int64)
        else:
            distinct_values = np.asarray([0.0])
            counts = np.asarray([zero_cnt], np.int64)

        self.min_val = float(distinct_values[0]) if len(distinct_values) \
            else 0.0
        self.max_val = float(distinct_values[-1]) if len(distinct_values) \
            else 0.0
        num_distinct = len(distinct_values)
        forced = list(forced_bounds) if forced_bounds else []

        if bin_type == BinType.NUMERICAL:
            self._find_bin_numerical(distinct_values, counts, num_distinct, max_bin,
                                     total_sample_cnt, min_data_in_bin, na_cnt, forced)
        else:
            self._find_bin_categorical(distinct_values, counts, max_bin,
                                       total_sample_cnt, na_cnt, min_data_in_bin)

        # trivial check + most-freq-bin / sparse-rate (reference bin.cpp:500-528)
        self.is_trivial = self.num_bin <= 1
        if min_split_data > 0 and not self.is_trivial:
            if not _splittable(self._cnt_in_bin, total_sample_cnt, min_split_data,
                               self.bin_type):
                self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = self.value_to_bin(0.0)
            total = max(1, total_sample_cnt)
            cnt = self._cnt_in_bin
            self.most_freq_bin = int(np.argmax(cnt))
            self.sparse_rate = float(cnt[self.default_bin]) / total
            max_sparse_rate = float(cnt[self.most_freq_bin]) / total
            # snap to the zero bin unless another bin dominates (>0.7)
            if self.most_freq_bin != self.default_bin and max_sparse_rate > np.float32(0.7):
                self.sparse_rate = max_sparse_rate
            else:
                self.most_freq_bin = self.default_bin
        else:
            self.sparse_rate = 1.0

    def _find_bin_numerical(self, distinct_values, counts, num_distinct, max_bin,
                            total_sample_cnt, min_data_in_bin, na_cnt, forced):
        def run(mb: int, total: int) -> List[float]:
            if forced:
                return _find_bin_with_forced(distinct_values, counts, mb, total,
                                             min_data_in_bin, forced)
            return _find_bin_zero_as_one(distinct_values, counts,
                                         mb, total, min_data_in_bin)

        if self.missing_type == MissingType.ZERO:
            bounds = run(max_bin, total_sample_cnt)
            if len(bounds) == 2:
                self.missing_type = MissingType.NONE
        elif self.missing_type == MissingType.NONE:
            bounds = run(max_bin, total_sample_cnt)
        else:  # NaN: reserve the last bin for NaN
            bounds = run(max_bin - 1, total_sample_cnt - na_cnt)
            bounds.append(float("nan"))
        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bin = len(bounds)

        # the scalar `while v > ub[i_bin]` walk over sorted distincts IS
        # a searchsorted('left'); the last REAL bound is +inf, so the
        # NaN tail (missing==NaN) is never reached
        n_real = self.num_bin - (1 if self.missing_type == MissingType.NAN
                                 else 0)
        dv = np.asarray(distinct_values, dtype=np.float64)
        pos = np.searchsorted(self.bin_upper_bound[:n_real], dv, side="left")
        cnt_in_bin = np.zeros(self.num_bin, np.int64)
        np.add.at(cnt_in_bin, pos, np.asarray(counts, dtype=np.int64))
        cnt_in_bin = cnt_in_bin.tolist()
        if self.missing_type == MissingType.NAN:
            cnt_in_bin[self.num_bin - 1] = na_cnt
        self._cnt_in_bin = cnt_in_bin
        self.default_bin = self.value_to_bin(0.0)

    def _find_bin_categorical(self, distinct_values, counts, max_bin,
                              total_sample_cnt, na_cnt, min_data_in_bin=3):
        """Count-sorted categorical binning (reference bin.cpp:425-497).

        Categories map to bins in descending-count order until 99% coverage;
        rare categories share the LAST bin (via the unseen->num_bin-1 rule in
        value_to_bin); a dedicated -1/NaN bin is added only when every
        category got a bin and NaNs exist.
        """
        # int(v) truncates toward zero; distincts sorted ascending and
        # non-negative truncation is monotone, so np.unique preserves the
        # scalar dict's first-occurrence (ascending-category) order that
        # the stable count sort below depends on
        iv = np.asarray(distinct_values, np.float64).astype(np.int64)
        cn = np.asarray(counts, np.int64)
        neg = iv < 0
        na_cnt += int(cn[neg].sum())
        cats, inv = np.unique(iv[~neg], return_inverse=True)
        ccnt = np.bincount(inv, weights=cn[~neg]).astype(np.int64) \
            if cats.size else np.zeros(0, np.int64)
        self.num_bin = 0
        rest_cnt = total_sample_cnt - na_cnt
        self._cnt_in_bin = []
        if rest_cnt <= 0:
            self.missing_type = MissingType.NONE
            return
        items = sorted(zip(cats.tolist(), ccnt.tolist()),
                       key=lambda kv: -kv[1])
        # avoid first bin being category 0 (reference bin.cpp:453-460)
        if items and items[0][0] == 0:
            if len(items) == 1:
                items.append((items[0][0] + 1, 0))
            items[0], items[1] = items[1], items[0]
        cut_cnt = int(np.float32((total_sample_cnt - na_cnt)) * np.float32(0.99))
        self.categorical_2_bin = {}
        self.bin_2_categorical = []
        used_cnt = 0
        mb = min(len(items), max_bin)
        cnt_in_bin: List[int] = []
        cur_cat = 0
        while cur_cat < len(items) and (used_cnt < cut_cnt or self.num_bin < mb):
            cat, cnt = items[cur_cat]
            if cnt < min_data_in_bin and cur_cat > 1:
                break
            self.bin_2_categorical.append(cat)
            self.categorical_2_bin[cat] = self.num_bin
            used_cnt += cnt
            cnt_in_bin.append(cnt)
            self.num_bin += 1
            cur_cat += 1
        # dedicated NaN bin only when all categories were consumed
        if cur_cat == len(items) and na_cnt > 0:
            self.bin_2_categorical.append(-1)
            self.categorical_2_bin[-1] = self.num_bin
            cnt_in_bin.append(0)
            self.num_bin += 1
        if cur_cat == len(items) and na_cnt == 0:
            self.missing_type = MissingType.NONE
        else:
            self.missing_type = MissingType.NAN
        if cnt_in_bin:
            cnt_in_bin[-1] += total_sample_cnt - used_cnt
        self._cnt_in_bin = cnt_in_bin

    @property
    def cnt_in_bin(self) -> List[int]:
        """Per-bin sample occupancy recorded by `find_bin` (reference
        ``BinMapper::cnt_in_bin``, bin.h:102) — the training reference
        the model-health profile captures.  Serialized by
        `to_dict`/`from_dict` so binary dataset caches and the
        distributed bin-mapper sync keep it; empty only for mappers
        from snapshots written before it existed."""
        return list(getattr(self, "_cnt_in_bin", []))

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Map one raw value to its bin (reference bin.h:472-508)."""
        if math.isnan(value):
            if self.missing_type == MissingType.NAN:
                return self.num_bin - 1
            value = 0.0
        if self.bin_type == BinType.NUMERICAL:
            ub = self.bin_upper_bound
            hi = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                hi -= 1
            return int(np.searchsorted(ub[:hi], value, side="left"))
        iv = int(value)
        if iv < 0:
            return self.num_bin - 1
        return self.categorical_2_bin.get(iv, self.num_bin - 1)

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin for a full column."""
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        if self.bin_type == BinType.NUMERICAL:
            has_nan = bool(nan_mask.any())
            vals = np.where(nan_mask, 0.0, values) if has_nan else values
            hi = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                hi -= 1
            out = np.searchsorted(self.bin_upper_bound[:hi], vals,
                                  side="left").astype(np.int32)
            if has_nan and self.missing_type == MissingType.NAN:
                out[nan_mask] = self.num_bin - 1
            return out
        # NaN: dedicated bin when missing==NaN, else treated as category 0
        nan_cat = -1 if self.missing_type == MissingType.NAN else 0
        ivals = np.where(nan_mask, nan_cat,
                         np.nan_to_num(values, nan=0.0)).astype(np.int64)
        out = np.full(values.shape, self.num_bin - 1, dtype=np.int32)
        for cat, b in self.categorical_2_bin.items():
            if cat >= 0:
                out[ivals == cat] = b
        out[ivals < 0] = self.num_bin - 1
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative raw value for a bin (used for model thresholds)."""
        if self.bin_type == BinType.NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # -- serialization (for distributed bin-mapper sync & binary cache) ----
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "is_trivial": self.is_trivial,
            "bin_type": int(self.bin_type),
            "missing_type": int(self.missing_type),
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "sparse_rate": self.sparse_rate,
            # sample occupancy travels with the mapper so the model-
            # health profile survives binary dataset caches and the
            # distributed bin-mapper sync (ISSUE 14); absent in files
            # written before it existed (from_dict defaults to [])
            "cnt_in_bin": [int(x) for x in
                           getattr(self, "_cnt_in_bin", [])],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.is_trivial = bool(d["is_trivial"])
        m.bin_type = BinType(d["bin_type"])
        m.missing_type = MissingType(d["missing_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        m.sparse_rate = float(d.get("sparse_rate", 0.0))
        m._cnt_in_bin = [int(x) for x in d.get("cnt_in_bin", [])]
        return m


def _splittable(cnt_in_bin: List[int], total_cnt: int, filter_cnt: int,
                bin_type: BinType) -> bool:
    """Inverse of reference NeedFilter (src/io/bin.cpp:54-76)."""
    if bin_type == BinType.NUMERICAL:
        sum_left = 0
        for c in cnt_in_bin[:-1]:
            sum_left += c
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return True
        return False
    if len(cnt_in_bin) <= 2:
        for c in cnt_in_bin[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return True
        return False
    return True
