"""Per-feature value -> bin quantization.

Behavioral re-implementation of the reference BinMapper
(reference src/io/bin.cpp:78-470, include/LightGBM/bin.h:65-230):

* numerical features: greedy equal-count bin boundary search
  (`GreedyFindBin`, bin.cpp:78) with the zero-as-one-bin variant
  (`FindBinWithZeroAsOneBin`, bin.cpp:256) that dedicates one bin to
  [-1e-35, 1e-35] and splits the budget between negative / positive values;
* categorical features: categories sorted by count, mapped to bins until 99%
  coverage, rare categories -> the NaN bin (bin.cpp:410-460);
* missing handling: None / Zero / NaN (bin.h:26-30) — with MissingType.NaN the
  last bin is reserved for NaN values;
* forced bin bounds (`forcedbins_filename`, bin.cpp:157-255).

Bin semantics: numerical bin `i` holds values v with
`bin_upper_bound[i-1] < v <= bin_upper_bound[i]`; the last real upper bound is
+inf.  `value_to_bin` therefore is a searchsorted over the upper bounds
(reference `BinMapper::ValueToBin`, bin.h:472-508).
"""

from __future__ import annotations

import enum
import math
from typing import Dict, List, Optional, Sequence

import numpy as np

K_ZERO_THRESHOLD = 1e-35  # reference include/LightGBM/meta.h:53
_F32_INF = float("inf")


class MissingType(enum.IntEnum):
    NONE = 0
    ZERO = 1
    NAN = 2


class BinType(enum.IntEnum):
    NUMERICAL = 0
    CATEGORICAL = 1


def _upper_bound(a: float) -> float:
    """Smallest double strictly greater than a (reference Common::GetDoubleUpperBound)."""
    return float(np.nextafter(a, np.inf))


def _equal_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a, inf) (reference Common::CheckDoubleEqualOrdered)."""
    return b <= np.nextafter(a, np.inf)


def greedy_find_bin(distinct_values: Sequence[float], counts: Sequence[int],
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-count boundary search (reference src/io/bin.cpp:78-155).

    Returns bin upper bounds; the last is +inf.
    """
    assert max_bin > 0
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct - 1):
            cur_cnt_inbin += counts[i]
            if cur_cnt_inbin >= min_data_in_bin:
                val = _upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _equal_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur_cnt_inbin = 0
        bounds.append(_F32_INF)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    # values with count >= mean size get their own bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = [False] * num_distinct
    for i in range(num_distinct):
        if counts[i] >= mean_bin_size:
            is_big[i] = True
            rest_bin_cnt -= 1
            rest_sample_cnt -= counts[i]
    # C++ float semantics: x/0 is inf (every distinct value "big" leaves
    # rest_bin_cnt == 0, reference bin.cpp:116 tolerates it); Python's /
    # would raise instead
    with np.errstate(divide="ignore", invalid="ignore"):
        mean_bin_size = float(np.float64(rest_sample_cnt)
                              / np.float64(rest_bin_cnt))

    uppers = [_F32_INF] * max_bin
    lowers = [_F32_INF] * max_bin
    bin_cnt = 0
    lowers[0] = distinct_values[0]
    cur_cnt_inbin = 0
    # 0.5f: the reference multiplies by a float literal (bin.cpp:131)
    half = np.float32(0.5)
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= counts[i]
        cur_cnt_inbin += counts[i]
        if (is_big[i] or cur_cnt_inbin >= mean_bin_size or
                (is_big[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * half))):
            uppers[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lowers[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                with np.errstate(divide="ignore", invalid="ignore"):
                    mean_bin_size = float(np.float64(rest_sample_cnt)
                                          / np.float64(rest_bin_cnt))
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _upper_bound((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _equal_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(_F32_INF)
    return bounds


def _find_bin_zero_as_one(distinct_values: Sequence[float], counts: Sequence[int],
                          max_bin: int, total_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """Zero-as-one-bin boundary search (reference src/io/bin.cpp:256-313)."""
    num_distinct = len(distinct_values)
    left_cnt_data = cnt_zero = right_cnt_data = 0
    for v, c in zip(distinct_values, counts):
        if v <= -K_ZERO_THRESHOLD:
            left_cnt_data += c
        elif v > K_ZERO_THRESHOLD:
            right_cnt_data += c
        else:
            cnt_zero += c

    left_cnt = num_distinct
    for i, v in enumerate(distinct_values):
        if v > -K_ZERO_THRESHOLD:
            left_cnt = i
            break

    bounds: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        left_max_bin = max(
            1, int(left_cnt_data / max(1, total_cnt - cnt_zero) * (max_bin - 1)))
        bounds = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    right_start = -1
    for i in range(left_cnt, num_distinct):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    right_max_bin = max_bin - 1 - len(bounds)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(distinct_values[right_start:],
                                       counts[right_start:], right_max_bin,
                                       right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(_F32_INF)
    assert len(bounds) <= max_bin
    return bounds


def _find_bin_with_forced(distinct_values: Sequence[float], counts: Sequence[int],
                          max_bin: int, total_cnt: int, min_data_in_bin: int,
                          forced_bounds: Sequence[float]) -> List[float]:
    """Forced-boundary variant (reference src/io/bin.cpp:157-255)."""
    num_distinct = len(distinct_values)
    left_cnt = num_distinct
    for i, v in enumerate(distinct_values):
        if v > -K_ZERO_THRESHOLD:
            left_cnt = i
            break
    right_start = -1
    for i in range(left_cnt, num_distinct):
        if distinct_values[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    bounds: List[float] = []
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bounds.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(_F32_INF)

    max_to_insert = max_bin - len(bounds)
    num_inserted = 0
    for b in forced_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bounds.append(float(b))
            num_inserted += 1
    bounds.sort()

    free_bins = max_bin - len(bounds)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_bounds = len(bounds)
    for i in range(n_bounds):
        cnt_in_bin = 0
        distinct_cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < num_distinct and distinct_values[value_ind] < bounds[i]:
            cnt_in_bin += counts[value_ind]
            distinct_cnt_in_bin += 1
            value_ind += 1
        bins_remaining = max_bin - n_bounds - len(bounds_to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / max(1, total_cnt)))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_bounds - 1:
            num_sub_bins = bins_remaining + 1
        new_bounds = greedy_find_bin(distinct_values[bin_start:value_ind],
                                     counts[bin_start:value_ind],
                                     num_sub_bins, cnt_in_bin, min_data_in_bin)
        bounds_to_add.extend(new_bounds[:-1])  # last is +inf
    bounds.extend(bounds_to_add)
    bounds.sort()
    assert len(bounds) <= max_bin
    return bounds


class BinMapper:
    """Quantizer for one feature (reference include/LightGBM/bin.h:65-230)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.is_trivial: bool = True
        self.bin_type: BinType = BinType.NUMERICAL
        self.missing_type: MissingType = MissingType.NONE
        self.bin_upper_bound: np.ndarray = np.array([_F32_INF])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0      # bin of value 0.0
        self.most_freq_bin: int = 0
        self.sparse_rate: float = 0.0

    # ------------------------------------------------------------------
    def find_bin(self, sample_values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: BinType = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False,
                 forced_bounds: Optional[Sequence[float]] = None) -> None:
        """Compute bin boundaries from sampled non-zero values.

        `sample_values` excludes (near-)zero values; zeros are implied by
        `total_sample_cnt - len(sample_values)` as in the reference
        (src/io/bin.cpp:325-390).  NaNs may be present and are counted as
        missing.
        """
        values = np.asarray(sample_values, dtype=np.float64)
        na_cnt = int(np.isnan(values).sum())
        values = values[~np.isnan(values)]

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE
        if self.missing_type != MissingType.NAN:
            na_cnt = 0

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - values.size - na_cnt)

        # distinct values with zero spliced in at its sorted position.
        # Vectorized equal-ordered grouping (the scalar loop was the
        # binning hot spot at ~10s/1M rows): consecutive values with
        # next <= nextafter(prev, inf) merge, keeping the LARGER value —
        # i.e. each group's last element — exactly like the sequential
        # merge (reference bin.cpp:332-352 semantics).
        values = np.sort(values, kind="stable")
        distinct_values: List[float] = []
        counts: List[int] = []
        if values.size:
            new_group = values[1:] > np.nextafter(values[:-1], np.inf)
            last_idx = np.flatnonzero(np.append(new_group, True))
            dv = values[last_idx]
            cn = np.diff(np.concatenate([[-1], last_idx]))
            # splice zero (its count is implied, never sampled) at its
            # ordered position; sampled values are never exactly 0.0 (the
            # caller filtered |v| <= kZeroThreshold), so the insertion
            # point is unambiguous.  An INTERIOR zero (negatives and
            # positives both present) is inserted even at count 0 — the
            # scalar loop and reference bin.cpp:341-344 do, and the extra
            # zero-count entry changes categorical bin assembly
            if dv.size:
                pos = int(np.searchsorted(dv, 0.0))
                if zero_cnt > 0 or 0 < pos < len(dv):
                    dv = np.insert(dv, pos, 0.0)
                    cn = np.insert(cn, pos, zero_cnt)
            distinct_values = dv.tolist()
            counts = cn.tolist()
        else:
            distinct_values = [0.0]
            counts = [zero_cnt]

        self.min_val = distinct_values[0] if distinct_values else 0.0
        self.max_val = distinct_values[-1] if distinct_values else 0.0
        num_distinct = len(distinct_values)
        forced = list(forced_bounds) if forced_bounds else []

        if bin_type == BinType.NUMERICAL:
            self._find_bin_numerical(distinct_values, counts, num_distinct, max_bin,
                                     total_sample_cnt, min_data_in_bin, na_cnt, forced)
        else:
            self._find_bin_categorical(distinct_values, counts, max_bin,
                                       total_sample_cnt, na_cnt, min_data_in_bin)

        # trivial check + most-freq-bin / sparse-rate (reference bin.cpp:500-528)
        self.is_trivial = self.num_bin <= 1
        if min_split_data > 0 and not self.is_trivial:
            if not _splittable(self._cnt_in_bin, total_sample_cnt, min_split_data,
                               self.bin_type):
                self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = self.value_to_bin(0.0)
            total = max(1, total_sample_cnt)
            cnt = self._cnt_in_bin
            self.most_freq_bin = int(np.argmax(cnt))
            self.sparse_rate = float(cnt[self.default_bin]) / total
            max_sparse_rate = float(cnt[self.most_freq_bin]) / total
            # snap to the zero bin unless another bin dominates (>0.7)
            if self.most_freq_bin != self.default_bin and max_sparse_rate > np.float32(0.7):
                self.sparse_rate = max_sparse_rate
            else:
                self.most_freq_bin = self.default_bin
        else:
            self.sparse_rate = 1.0

    def _find_bin_numerical(self, distinct_values, counts, num_distinct, max_bin,
                            total_sample_cnt, min_data_in_bin, na_cnt, forced):
        def run(mb: int, total: int) -> List[float]:
            if forced:
                return _find_bin_with_forced(distinct_values, counts, mb, total,
                                             min_data_in_bin, forced)
            return _find_bin_zero_as_one(distinct_values, counts,
                                         mb, total, min_data_in_bin)

        if self.missing_type == MissingType.ZERO:
            bounds = run(max_bin, total_sample_cnt)
            if len(bounds) == 2:
                self.missing_type = MissingType.NONE
        elif self.missing_type == MissingType.NONE:
            bounds = run(max_bin, total_sample_cnt)
        else:  # NaN: reserve the last bin for NaN
            bounds = run(max_bin - 1, total_sample_cnt - na_cnt)
            bounds.append(float("nan"))
        self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        self.num_bin = len(bounds)

        cnt_in_bin = [0] * self.num_bin
        i_bin = 0
        for v, c in zip(distinct_values, counts):
            while v > self.bin_upper_bound[i_bin]:
                i_bin += 1
            cnt_in_bin[i_bin] += c
        if self.missing_type == MissingType.NAN:
            cnt_in_bin[self.num_bin - 1] = na_cnt
        self._cnt_in_bin = cnt_in_bin
        self.default_bin = self.value_to_bin(0.0)

    def _find_bin_categorical(self, distinct_values, counts, max_bin,
                              total_sample_cnt, na_cnt, min_data_in_bin=3):
        """Count-sorted categorical binning (reference bin.cpp:425-497).

        Categories map to bins in descending-count order until 99% coverage;
        rare categories share the LAST bin (via the unseen->num_bin-1 rule in
        value_to_bin); a dedicated -1/NaN bin is added only when every
        category got a bin and NaNs exist.
        """
        cat_counts: Dict[int, int] = {}
        for v, c in zip(distinct_values, counts):
            iv = int(v)
            if iv < 0:
                na_cnt += c
            else:
                cat_counts[iv] = cat_counts.get(iv, 0) + c
        self.num_bin = 0
        rest_cnt = total_sample_cnt - na_cnt
        self._cnt_in_bin = []
        if rest_cnt <= 0:
            self.missing_type = MissingType.NONE
            return
        items = sorted(cat_counts.items(), key=lambda kv: -kv[1])
        # avoid first bin being category 0 (reference bin.cpp:453-460)
        if items and items[0][0] == 0:
            if len(items) == 1:
                items.append((items[0][0] + 1, 0))
            items[0], items[1] = items[1], items[0]
        cut_cnt = int(np.float32((total_sample_cnt - na_cnt)) * np.float32(0.99))
        self.categorical_2_bin = {}
        self.bin_2_categorical = []
        used_cnt = 0
        mb = min(len(items), max_bin)
        cnt_in_bin: List[int] = []
        cur_cat = 0
        while cur_cat < len(items) and (used_cnt < cut_cnt or self.num_bin < mb):
            cat, cnt = items[cur_cat]
            if cnt < min_data_in_bin and cur_cat > 1:
                break
            self.bin_2_categorical.append(cat)
            self.categorical_2_bin[cat] = self.num_bin
            used_cnt += cnt
            cnt_in_bin.append(cnt)
            self.num_bin += 1
            cur_cat += 1
        # dedicated NaN bin only when all categories were consumed
        if cur_cat == len(items) and na_cnt > 0:
            self.bin_2_categorical.append(-1)
            self.categorical_2_bin[-1] = self.num_bin
            cnt_in_bin.append(0)
            self.num_bin += 1
        if cur_cat == len(items) and na_cnt == 0:
            self.missing_type = MissingType.NONE
        else:
            self.missing_type = MissingType.NAN
        if cnt_in_bin:
            cnt_in_bin[-1] += total_sample_cnt - used_cnt
        self._cnt_in_bin = cnt_in_bin

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Map one raw value to its bin (reference bin.h:472-508)."""
        if math.isnan(value):
            if self.missing_type == MissingType.NAN:
                return self.num_bin - 1
            value = 0.0
        if self.bin_type == BinType.NUMERICAL:
            ub = self.bin_upper_bound
            hi = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                hi -= 1
            return int(np.searchsorted(ub[:hi], value, side="left"))
        iv = int(value)
        if iv < 0:
            return self.num_bin - 1
        return self.categorical_2_bin.get(iv, self.num_bin - 1)

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin for a full column."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(values.shape, dtype=np.int32)
        nan_mask = np.isnan(values)
        if self.bin_type == BinType.NUMERICAL:
            vals = np.where(nan_mask, 0.0, values)
            hi = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                hi -= 1
            out = np.searchsorted(self.bin_upper_bound[:hi], vals,
                                  side="left").astype(np.int32)
            if self.missing_type == MissingType.NAN:
                out[nan_mask] = self.num_bin - 1
            return out
        # NaN: dedicated bin when missing==NaN, else treated as category 0
        nan_cat = -1 if self.missing_type == MissingType.NAN else 0
        ivals = np.where(nan_mask, nan_cat,
                         np.nan_to_num(values, nan=0.0)).astype(np.int64)
        out = np.full(values.shape, self.num_bin - 1, dtype=np.int32)
        for cat, b in self.categorical_2_bin.items():
            if cat >= 0:
                out[ivals == cat] = b
        out[ivals < 0] = self.num_bin - 1
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative raw value for a bin (used for model thresholds)."""
        if self.bin_type == BinType.NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # -- serialization (for distributed bin-mapper sync & binary cache) ----
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "is_trivial": self.is_trivial,
            "bin_type": int(self.bin_type),
            "missing_type": int(self.missing_type),
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
            "sparse_rate": self.sparse_rate,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.is_trivial = bool(d["is_trivial"])
        m.bin_type = BinType(d["bin_type"])
        m.missing_type = MissingType(d["missing_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        m.sparse_rate = float(d.get("sparse_rate", 0.0))
        m._cnt_in_bin = []
        return m


def _splittable(cnt_in_bin: List[int], total_cnt: int, filter_cnt: int,
                bin_type: BinType) -> bool:
    """Inverse of reference NeedFilter (src/io/bin.cpp:54-76)."""
    if bin_type == BinType.NUMERICAL:
        sum_left = 0
        for c in cnt_in_bin[:-1]:
            sum_left += c
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return True
        return False
    if len(cnt_in_bin) <= 2:
        for c in cnt_in_bin[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return True
        return False
    return True
