"""`python -m lightgbm_tpu ...` = the reference CLI binary (src/main.cpp).

Backend resilience: when the default accelerator backend cannot initialize
(dead axon tunnel, or JAX_PLATFORM_NAME=cpu fighting a sitecustomize-latched
JAX_PLATFORMS=axon), fall back to the CPU backend with a warning instead of
dying — the CLI analog of bench.py's probe-and-degrade.
"""

import sys


def _ensure_backend() -> None:
    # Probe OUT-OF-PROCESS first: a hung tunnel must hit the subprocess
    # timeout, not hang this process (in-process jax.devices() has no
    # timeout and cannot be interrupted once the plugin blocks).  Skipped
    # entirely on hosts without the tunneled backend, and cached in an env
    # var so child/repeat invocations don't re-pay the probe.
    import os

    from .utils.backend import (backend_health, pin_cpu_backend,
                                probe_default_backend)
    from .utils.log import Log

    health = backend_health()
    if health == "ok":
        return
    if health == "probe":
        cached = os.environ.get("LGBM_BACKEND_PROBE_RESULT")
        if cached == "ok":
            return
        if cached != "failed":
            timeout_s = float(
                os.environ.get("LGBM_BACKEND_PROBE_TIMEOUT", 60))
            platform = probe_default_backend(timeout_s=timeout_s, retries=0)
            os.environ["LGBM_BACKEND_PROBE_RESULT"] = (
                "failed" if platform is None else "ok")
            if platform is not None:
                return
    pin_cpu_backend()
    import jax

    jax.devices()  # raises if even CPU is broken
    Log.warning("accelerator backend unavailable "
                f"(backend {health}); falling back to CPU")


_ensure_backend()

from .application import main  # noqa: E402

sys.exit(main())
