"""`python -m lightgbm_tpu ...` = the reference CLI binary (src/main.cpp)."""

import sys

from .application import main

sys.exit(main())
