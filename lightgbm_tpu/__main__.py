"""`python -m lightgbm_tpu ...` = the reference CLI binary (src/main.cpp).

Backend resilience: when the default accelerator backend cannot initialize
(dead axon tunnel, or JAX_PLATFORM_NAME=cpu fighting a sitecustomize-latched
JAX_PLATFORMS=axon), fall back to the CPU backend with a warning instead of
dying — the CLI analog of bench.py's probe-and-degrade.
"""

import sys


def _ensure_backend() -> None:
    # Probe OUT-OF-PROCESS first: a hung tunnel must hit the subprocess
    # timeout, not hang this process (in-process jax.devices() has no
    # timeout and cannot be interrupted once the plugin blocks).
    from .utils.backend import ensure_backend_or_cpu

    ensure_backend_or_cpu()
    import jax

    jax.devices()  # raises if even CPU is broken


_ensure_backend()

from .application import main  # noqa: E402

sys.exit(main())
