"""Benchmark: Higgs-shaped GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark (BASELINE.md: Higgs, 500 trees,
255 leaves, lr=0.1 — 238.5 s on 2x E5-2670v3, i.e. 2.096 boosting iters/s).
The real Higgs dataset cannot be fetched here (no egress), so the data is a
seeded synthetic with Higgs dimensions (1M rows x 28 dense features) and a
nonlinear separable structure; histogram/split work depends only on shape,
bins, and leaf count, so iters/sec is comparable.

Robustness (round-1 postmortem, BENCH_r01 rc=1): the tunneled TPU backend
('axon') can be down or hang during init.  The default backend is probed in
a throwaway subprocess with a hard timeout + bounded retries; on failure the
benchmark pins the CPU backend and runs a smaller problem so the round
still produces a (clearly-marked, degraded) number instead of a stack trace.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

N_FEATURES = 28
WARMUP_ITERS = 3
BASELINE_ITERS_PER_SEC = 500.0 / 238.5  # reference Higgs CPU (BASELINE.md)


def make_data(n, f, seed=42):
    # real data preferred when present: LIGHTGBM_TPU_BENCH_DATA points at
    # a labels-first CSV/TSV (e.g. the real HIGGS.csv) — both frameworks
    # then train on identical rows and the AUC half of the north-star
    # metric becomes directly comparable (tools/auc_parity.py)
    real = os.environ.get("LIGHTGBM_TPU_BENCH_DATA", "")
    if real:
        if not os.path.exists(real):
            raise FileNotFoundError(
                f"LIGHTGBM_TPU_BENCH_DATA={real!r} does not exist — "
                "refusing to silently fall back to synthetic data")
        # pandas' C parser is ~20x np.loadtxt and streams nrows — at
        # HIGGS scale (11M rows) loadtxt would dominate bench startup
        import pandas as pd

        raw = pd.read_csv(real, header=None, nrows=n, comment="#",
                          sep="," if real.endswith(".csv") else r"\s+",
                          dtype=np.float64).to_numpy()
        if raw.ndim != 2:
            raw = raw.reshape(1, -1)
        if raw.shape[1] < f + 1:
            raise ValueError(
                f"{real}: {raw.shape[1]} columns, need label + {f} "
                "features")
        y, X = raw[:, 0].astype(np.float64), raw[:, 1:1 + f]
        return np.ascontiguousarray(X, np.float64), y
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=(f,))
    logits = (X[:, :8] ** 2 - 1.0).sum(axis=1) * 0.3 + X @ w * 0.5
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def hist_rows_per_sec(bins_np, num_bins, precision, reps=3):
    """Histogram-kernel rows/s at `precision` over an already-binned
    matrix: times the root-histogram contraction (build_histogram_t, the
    same op the grower's hot loop runs per round) on whatever backend is
    active — so degraded CPU rounds still record the int8-vs-hilo kernel
    ratio even when the headline iters/s is not comparable."""
    import jax
    from lightgbm_tpu.ops.histogram import (bench_hist_operands,
                                            build_histogram_t)
    from lightgbm_tpu.utils.backend import host_sync

    block = min(16384, bins_np.shape[0])
    bins_tb, stats, n_use = bench_hist_operands(bins_np, precision, block)
    fn = jax.jit(lambda b, s: build_histogram_t(b, s, num_bins, precision))
    host_sync(fn(bins_tb, stats))  # compile
    rates = []
    for _ in range(max(reps, 3)):
        t0 = time.time()
        host_sync(fn(bins_tb, stats))
        rates.append(n_use / max(time.time() - t0, 1e-9))
    return rates


def fused_frontier_rows_per_sec_probe(bins_np, num_bins, reps=3, k=8):
    """Fused frontier megakernel rows/s (histogram + in-kernel 2K-child
    split scan, ops/fused.py fused_hist_scan) at int8 over an already-
    binned matrix — the one-program frontier step ISSUE 18 makes the
    grower's measured default on validated backends."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.fused import fused_hist_scan
    from lightgbm_tpu.ops.histogram import bench_hist_operands

    block = min(8192, bins_np.shape[0])
    bins_tb, stats, n_use = bench_hist_operands(bins_np, "int8", block)
    nb = n_use // block
    F = bins_np.shape[1]
    rng = np.random.default_rng(0)
    leaf_b = jnp.asarray(rng.integers(0, k, size=n_use).astype(np.int32)
                         .reshape(nb, block))
    slots = jnp.arange(k, dtype=jnp.int32)
    C = 2 * k
    ctx_np = np.zeros((C + 1, 8), np.float32)
    ctx_np[:C, 0] = 100.0
    ctx_np[:C, 1] = 200.0
    ctx_np[:C, 2] = float(n_use) / C
    ctx_np[:C, 3] = -1e30
    ctx_np[:C, 4] = 1e30
    ctx_np[:C, 5] = (np.arange(C) % 2).astype(np.float32)
    ctx_np[C, :3] = (0.5, 0.25, 1.0)
    ctx = jnp.asarray(ctx_np)
    meta_i = jnp.zeros((F, 8), jnp.int32).at[:, 0].set(num_bins)
    meta_f = jnp.ones((F, 8), jnp.float32)
    parent = jnp.full((k, F, num_bins, 3), n_use // k, jnp.int32)
    kw = dict(l1=0.0, l2=1.0, max_delta_step=0.0, min_data_in_leaf=1.0,
              min_sum_hessian=1e-3, min_gain_to_split=0.0)
    fn = jax.jit(lambda b, s, l: fused_hist_scan(
        b, s, l, slots, parent, ctx, meta_i, meta_f, num_bins, "int8",
        split_kw=kw))
    # block_until_ready: the kernel returns a (hist, records) pytree
    jax.block_until_ready(fn(bins_tb, stats, leaf_b))  # compile
    rates = []
    for _ in range(max(reps, 3)):
        t0 = time.time()
        jax.block_until_ready(fn(bins_tb, stats, leaf_b))
        rates.append(n_use / max(time.time() - t0, 1e-9))
    return rates


def autotune_resolve_ms_probe(num_bins):
    """Wall ms of the steady-state autotune path: load the persisted
    profile and resolve one shape bucket (the cost every learner
    construction under tpu_autotune=load pays).  The measurement tunes a
    throwaway profile first so the timed part is pure load+resolve."""
    import tempfile

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.autotune import resolve_autotune

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "autotune_profile.json")
        cfg_tune = Config({"objective": "binary", "tpu_autotune": "tune",
                           "tpu_autotune_profile": path})
        resolve_autotune(cfg_tune, 8192, 8, num_bins, "int8")
        cfg_load = Config({"objective": "binary", "tpu_autotune": "load",
                           "tpu_autotune_profile": path})
        t0 = time.time()
        entry = resolve_autotune(cfg_load, 8192, 8, num_bins, "int8")
        ms = (time.time() - t0) * 1e3
        if entry is None:
            raise RuntimeError("autotune round-trip lost its own entry")
    return ms


def spread(rates):
    """(median, min) of a repeat series — every timed metric reports its
    own variance (VERDICT item 7) instead of a single unqualified
    number."""
    return float(np.median(rates)), float(np.min(rates))


def prior_bench_record():
    """(filename, parsed record) of the newest committed BENCH_r*.json —
    the baseline the compile_s / n_programs regression note compares
    against."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                   key=lambda p: [int(s) for s in re.findall(r"\d+", p)])
    for path in reversed(files):
        try:
            with open(path) as fh:
                rec = json.load(fh)
            parsed = rec.get("parsed", rec)
            if isinstance(parsed, dict) and "compile_s" in parsed:
                return os.path.basename(path), parsed
        except (OSError, ValueError):
            continue
    return None, None


def run(n_rows, num_leaves, max_bin, bench_iters, degraded, comparable):
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.booster import Booster

    t_data = time.time()
    X, y = make_data(n_rows, N_FEATURES)
    data_s = time.time() - t_data

    # telemetry (ISSUE 10): every timed segment below routes through the
    # metrics registry (obs.timed / phase histograms) instead of ad-hoc
    # stopwatches, so the numbers the bench prints are the numbers a
    # Prometheus scrape of the same run would see.  BENCH_TELEMETRY=
    # trace additionally writes a Chrome trace under BENCH_TRACE_DIR.
    from lightgbm_tpu import obs

    if os.environ.get("BENCH_TELEMETRY") or obs.mode() == "off":
        bench_mode = os.environ.get("BENCH_TELEMETRY", "metrics")
        if bench_mode == "off":
            # the bench READS its segment walls back from the registry,
            # so metrics is its floor — "off" would IndexError at the
            # first readback
            bench_mode = "metrics"
        obs.configure(mode=bench_mode,
                      trace_dir=os.environ.get("BENCH_TRACE_DIR") or None)

    # ingest phase split (sketch = bin finding, binning = value->bin,
    # layout = the learner's device-layout step, captured below after
    # Booster construction) — accumulated in the registry as
    # lgbm_phase_seconds_total{phase=...}
    from lightgbm_tpu.utils import timer as phase_timer

    phase_timer.reset()
    t_bin = time.time()
    ds = lgb.Dataset(X, label=y, params={"max_bin": max_bin})
    ds.construct()
    if ds._inner._ingest_bins is not None:
        # device ingest dispatches async; the honest rows/s number
        # waits for the binned matrix to actually exist
        jax.block_until_ready(ds._inner._ingest_bins)
    bin_s = time.time() - t_bin
    ingest_rows_per_sec = n_rows / max(bin_s, 1e-9)
    n_eval = min(50000, n_rows)
    X_eval = X[:n_eval].copy()
    del X

    params = {"objective": "binary", "num_leaves": num_leaves,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "max_bin": max_bin,
              # the benchmark pins its exact shape by default: no bucket
              # padding (tpu_shape_buckets trades ~1/buckets throughput for
              # compile-cache hits across DIFFERENT datasets, which a
              # fixed-shape benchmark never needs).  BENCH_SHAPE_BUCKETS=32
              # measures the shipping bucketed default instead, so the
              # configuration users actually get also has a perf record.
              "tpu_shape_buckets": int(os.environ.get(
                  "BENCH_SHAPE_BUCKETS", 0))}
    # persistent compilation cache (BENCH_COMPILE_CACHE=<dir>): the first
    # run pays the cold compile, repeats deserialize — compile_s plus the
    # cold/warm marker below quantifies the tail the cache removes
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE", "")
    cache_state = "off"
    if cache_dir:
        params["tpu_compile_cache_dir"] = cache_dir
        # probe the EFFECTIVE directory enable_compilation_cache will
        # resolve: CPU-pinned runs write a host-fingerprinted subdir, so
        # listing the root would call a cold CPU run "warm" whenever a
        # TPU run had populated the root
        from lightgbm_tpu.utils.backend import (_cpu_is_only_backend,
                                                _host_fingerprint)

        eff_dir = cache_dir
        if (os.environ.get("LIGHTGBM_TPU_CPU_PINNED")
                or _cpu_is_only_backend()):
            eff_dir = os.path.join(cache_dir, f"cpu-{_host_fingerprint()}")
        try:
            cache_state = "warm" if os.listdir(eff_dir) else "cold"
        except OSError:
            cache_state = "cold"
    # retrace audit: every ledgered jit site records its compiled
    # programs, so the round carries n_programs beside compile_s — a
    # future PR that doubles the program zoo fails the regression note
    # loudly instead of silently inflating the compile tail
    from lightgbm_tpu.utils.compile_ledger import LEDGER

    LEDGER.enable()
    # ISSUE 12: capture each program's re-lowerable specs so the round
    # carries a per-program cost table (flops / bytes accessed; HBM
    # byte fields where a backend reports them) beside n_programs
    LEDGER.enable_capture()
    LEDGER.reset()
    from lightgbm_tpu.obs import resources

    resources.reset_phase_peaks()
    bst = Booster(params=params, train_set=ds)
    # snapshot ingest phases NOW: later valid-set constructs would
    # double-count sketch/binning
    phases = dict(phase_timer.summary())
    from lightgbm_tpu.utils.backend import host_sync

    def _segments(tag, k=3):
        """The last k registry-recorded walls for one bench segment.
        The readback REFUSES a truncated ring shorter than the request:
        a silently under-counted repeat series would publish a median
        over the wrong repeats (ISSUE 12 satellite)."""
        samples, truncated = obs.REGISTRY.histogram_samples(
            "lgbm_timed_seconds", with_truncated=True, name=tag)
        if truncated and len(samples) < k:
            raise RuntimeError(
                f"bench segment {tag!r}: sample ring truncated below "
                f"the {k} requested repeats — raise tpu_obs_ring_samples")
        return samples[-k:]

    with obs.timed("bench/compile"):
        for _ in range(WARMUP_ITERS):
            bst.update()
        host_sync(bst._driver.train_scores.scores)
    compile_s = _segments("bench/compile", 1)[0]
    n_programs_train = LEDGER.n_programs()

    # >=3 timed segments so the headline carries its own variance
    # (median beside min); segments hold >=2 iters so the per-segment
    # host_sync doesn't serialize every single dispatch
    seg_iters = max(round(bench_iters / 3), 2)
    for _ in range(3):
        with obs.timed("bench/train_segment"):
            for _ in range(seg_iters):
                bst.update()
            host_sync(bst._driver.train_scores.scores)
    seg_walls = _segments("bench/train_segment")
    seg_rates = [seg_iters / max(w, 1e-9) for w in seg_walls]
    train_s = sum(seg_walls)
    bench_iters = 3 * seg_iters
    iters_per_sec, iters_per_sec_min = spread(seg_rates)
    # snapshot the TRAIN peak NOW: peak_bytes_in_use is a process-
    # lifetime high-water mark with no reset, so reading it after the
    # predict/serve sections would attribute their peaks to training
    train_peak_hbm_bytes = resources.peak_hbm_bytes()

    # prediction throughput: full-forest raw predict rows/s on the path
    # the configuration would actually use (device bin-space traversal on
    # TPU, native walker otherwise)
    bst.predict(X_eval, raw_score=True)  # warm (pack + compile)
    for _ in range(3):
        with obs.timed("bench/predict"):
            bst.predict(X_eval, raw_score=True)
    pred_rates = [n_eval / max(w, 1e-9) for w in _segments("bench/predict")]
    predict_rows_per_sec, predict_rows_per_sec_min = spread(pred_rates)
    # sanity AUC BEFORE the eval-overhead block: its extra update() calls
    # would otherwise make the recorded train_auc describe a model
    # trained more than bench_iters iterations
    pred = bst.predict(X_eval)

    # serving throughput: closed-loop hammer through the registry +
    # micro-batcher (lightgbm_tpu/serving) over the same booster —
    # measures the path a long-lived inference service actually runs
    # (warmup'd row buckets, coalesced launches), not bare predict
    from lightgbm_tpu.serving import ServingSession

    serve_rows = min(1024 if degraded else 4096, n_eval)
    serve_threads, serve_reqs = 4, 8
    sess = ServingSession(params={
        "serving_max_batch_rows": serve_rows, "verbosity": -1})
    sess.load("bench", booster=bst)  # packs + warms every row bucket
    Xs = X_eval[:serve_rows]

    serve_errors = []

    def _hammer():
        try:
            for _ in range(serve_reqs):
                sess.predict("bench", Xs, raw_score=True)
        except Exception as exc:  # surfaced below: a dead thread must
            serve_errors.append(exc)  # not silently inflate the number

    import threading as _threading

    serve_rates = []
    for _ in range(3):
        workers = [_threading.Thread(target=_hammer)
                   for _ in range(serve_threads)]
        t_serve = time.time()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        serve_s = max(time.time() - t_serve, 1e-9)
        if serve_errors:
            raise serve_errors[0]
        serve_rates.append(serve_threads * serve_reqs * serve_rows
                           / serve_s)
    serve_rows_per_sec, serve_rows_per_sec_min = spread(serve_rates)
    serve_p99_ms = sess.stats()["latency_p99_ms"]
    # ISSUE 12: what this model costs resident in the registry — the
    # packed device-table bytes the serve_model_hbm_bytes gauge tracks
    serve_model_hbm_bytes = int(sess.registry.resolve("bench").hbm_bytes)

    # drift-monitor overhead (ISSUE 14): the same entry-level predict
    # loop with the sampled drift accumulator enabled vs disabled, one
    # scrape (absorb + PSI/JS) amortized per window — the number the
    # <1% telemetry gate bounds for the OFF configuration, published so
    # bench_diff can watch the ON cost too.  min-of-3 windows per arm
    # to wash container stalls
    entry = sess.registry.resolve("bench")
    drift_reps = 10
    Xd = X_eval[:min(512, serve_rows)]

    def _drift_wall():
        t0 = time.time()
        for _ in range(drift_reps):
            entry.predict(Xd, raw_score=True)
        if entry.drift is not None:
            entry.drift.snapshot()
        return time.time() - t0

    entry.predict(Xd, raw_score=True)  # warm
    monitor, entry.drift = entry.drift, None
    off_wall = min(_drift_wall() for _ in range(3))
    entry.drift = monitor
    on_wall = min(_drift_wall() for _ in range(3))
    # clamped at 0: a negative measurement is container noise, and
    # bench_diff's relative gate needs a sane baseline sign
    drift_overhead_pct = max(100.0 * (on_wall - off_wall)
                             / max(off_wall, 1e-9), 0.0)
    sess.close()

    # overload-ramp goodput (ISSUE 11): paced open-loop load at ~4x the
    # closed-loop rate above, smaller requests so admission/batching do
    # real work — serve_goodput_rows_per_sec is the accepted-rows
    # throughput UNDER overload (sheds absorbing the excess), and
    # serve_shed_pct the fraction refused with 429/503/504 instead of
    # queueing into timeout collapse
    import importlib.util as _ilu

    _sb_spec = _ilu.spec_from_file_location(
        "_serve_bench", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools", "serve_bench.py"))
    _sb = _ilu.module_from_spec(_sb_spec)
    _sb_spec.loader.exec_module(_sb)
    ramp_rows = min(256, serve_rows)
    sess2 = ServingSession(params={
        "serving_max_batch_rows": serve_rows, "verbosity": -1})
    sess2.load("bench", booster=bst)
    ramp_qps = 4.0 * serve_rows_per_sec / max(ramp_rows, 1)
    r_ok, r_shed, r_err, r_dt = _sb.run_paced_counted(
        sess2, "bench", X_eval[:ramp_rows], ramp_rows, serve_threads,
        ramp_qps, 2.0 if degraded else 4.0,
        deadline_ms=4.0 * float(sess2.config.serving_slo_ms))
    if r_err:
        raise RuntimeError(f"serve ramp surfaced {r_err} errors to "
                           "accepted requests")
    offered = max(r_ok + r_shed + r_err, 1)
    serve_goodput_rows_per_sec = r_ok * ramp_rows / max(r_dt, 1e-9)
    serve_shed_pct = 100.0 * r_shed / offered
    sess2.close()

    # per-iteration valid-eval overhead the training loop pays when early
    # stopping is on: LIVE update+eval iterations (per-tree valid scoring
    # + materialize + metric fetch) minus the plain training it/s above —
    # timing eval_valid() alone after training would miss the incremental
    # device tree-scoring this path exists to speed up
    vd = ds.create_valid(X_eval, label=y[:n_eval])
    bst.add_valid(vd, "valid")
    bst.update()
    bst.eval_valid()  # warm (replay + compile)
    host_sync(bst._driver.train_scores.scores)
    eval_walls = []
    for _ in range(3):
        t_eval = time.time()
        bst.update()
        bst.eval_valid()
        host_sync(bst._driver.train_scores.scores)
        eval_walls.append(time.time() - t_eval)
    eval_med, _ = spread(eval_walls)
    eval_ms_per_iter = max(eval_med - train_s / bench_iters, 0.0) * 1e3

    # robustness cost (ISSUE 7): interval-checkpointed training vs plain
    # training over equal segments -> checkpoint_overhead_pct, plus the
    # wall to rebuild a training booster from the newest bundle
    # (resume_s) — tracked beside the perf metrics so fault tolerance
    # never silently taxes the hot loop
    import shutil as _shutil
    import tempfile as _tempfile

    from lightgbm_tpu.utils.checkpoint import (CheckpointManager,
                                               restore_checkpoint,
                                               save_checkpoint)

    ck_iters = max(seg_iters, 2)
    t0 = time.time()
    for _ in range(ck_iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    plain_s = max(time.time() - t0, 1e-9)
    ck_dir = _tempfile.mkdtemp(prefix="bench-ckpt-")
    try:
        manager = CheckpointManager(ck_dir, keep=2)
        t0 = time.time()
        for _ in range(ck_iters):
            bst.update()
            save_checkpoint(bst, manager)
        host_sync(bst._driver.train_scores.scores)
        ck_s = max(time.time() - t0, 1e-9)
        checkpoint_overhead_pct = max(ck_s - plain_s, 0.0) / plain_s * 100.0
        t0 = time.time()
        bst_resumed = Booster(params=params, train_set=ds)
        restore_checkpoint(bst_resumed, manager)
        resume_s = time.time() - t0
        del bst_resumed

        # ISSUE 8: elastic resume — the same bundle restored onto a
        # DIFFERENT shard topology (2-way data mesh when the backend has
        # the devices; degenerates to same-topology resume on 1 device,
        # still timing the elastic validation path)
        p_el = dict(params)
        if len(jax.devices()) >= 2:
            p_el.update(tree_learner="data", num_machines=2)
        t0 = time.time()
        bst_el = Booster(params=p_el, train_set=ds)
        restore_checkpoint(bst_el, manager)
        resume_elastic_s = time.time() - t0
        del bst_el

        # ISSUE 8: watchdog recovery — injected collective hang ->
        # structured timeout -> final-checkpoint flush -> rebuild +
        # resume + one boosting iteration (the full degrade-and-recover
        # cycle a hung peer costs)
        from lightgbm_tpu.parallel.collective import CollectiveTimeout
        from lightgbm_tpu.parallel.metric_sync import sync_sums
        from lightgbm_tpu.utils import faultline as _faultline
        from lightgbm_tpu.utils.checkpoint import flush_checkpoint

        _faultline.reset()
        _faultline.arm("collective_sync", action="hang")
        t0 = time.time()
        try:
            sync_sums([1.0])
        except CollectiveTimeout:
            pass
        _faultline.reset()
        flush_checkpoint(bst, manager)
        bst_rec = Booster(params=params, train_set=ds)
        restore_checkpoint(bst_rec, manager)
        bst_rec.update()
        host_sync(bst_rec._driver.train_scores.scores)
        collective_timeout_recovery_s = time.time() - t0
        del bst_rec
    finally:
        _shutil.rmtree(ck_dir, ignore_errors=True)

    # ISSUE 15: OOM recovery — injected RESOURCE_EXHAUSTED at the next
    # guarded train-step allocation -> atomic rollback -> one
    # degradation-ladder step -> settled completion, timed end to end.
    # Classification keys on the error SHAPE, which the injection
    # reproduces, so the number is real on every backend
    from lightgbm_tpu.utils import faultline as _fl
    from lightgbm_tpu.utils import membudget as _membudget

    _fl.reset()
    t0 = time.time()
    _fl.arm("device_alloc", action="oom", at=1)
    bst.update()
    host_sync(bst._driver.train_scores.scores)
    oom_recovery_s = time.time() - t0
    _fl.reset()

    # headroom between the enforced HBM budget and the observed train
    # peak (null on CPU like the other memory_stats-derived fields: no
    # capacity report means no budget resolves)
    _budget = _membudget.budget_bytes(bst._driver.config)
    hbm_budget_headroom_bytes = (
        None if _budget is None or train_peak_hbm_bytes is None
        else int(_budget) - int(train_peak_hbm_bytes))

    # ISSUE 16: out-of-core streaming — rows-beyond-HBM scaling curve.
    # Train the streamed layout on 1x/2x/4x of a base row count with the
    # SAME stream block size throughout: the 1x point stands in for "at
    # the resident cap", 2x/4x are datasets the resident layout could
    # not hold.  stream_rows_per_sec is the 4x point (the headline
    # out-of-core number); stream_overlap_pct is the fraction of the
    # estimated H2D copy wall hidden behind histogram contractions,
    # accumulated across every timed tree
    stream_base = max(min(n_rows // 4, 65_536), 8192)
    stream_iters = 2
    stream_scaling = {}
    stream_overlap_est = stream_overlap_hidden = 0.0
    stream_rows_per_sec = 0.0
    X_st, y_st = make_data(4 * stream_base, N_FEATURES, seed=7)
    for scale in (1, 2, 4):
        ns = stream_base * scale
        p_st = {"objective": "binary", "num_leaves": num_leaves,
                "max_bin": max_bin, "verbosity": -1,
                "tpu_stream_mode": "streamed",
                "tpu_stream_block_rows": max(stream_base // 2, 4096)}
        ds_st = lgb.Dataset(X_st[:ns], label=y_st[:ns], params=p_st)
        bst_st = Booster(params=p_st, train_set=ds_st)
        bst_st.update()                         # warm compiles
        wall = 0.0
        for _ in range(stream_iters):
            bst_st.update()
            s = bst_st._driver.learner.stream_stats
            wall += s["tree_wall_s"]
            stream_overlap_est += s["copy_est_s"]
            stream_overlap_hidden += (s["overlap_pct"] / 100.0
                                      * s["copy_est_s"])
        stream_scaling[f"{scale}x"] = round(
            ns * stream_iters / max(wall, 1e-9), 0)
        stream_rows_per_sec = stream_scaling[f"{scale}x"]
        del bst_st, ds_st
    del X_st, y_st
    stream_overlap_pct = (100.0 * stream_overlap_hidden
                          / max(stream_overlap_est, 1e-12))

    # histogram-kernel throughput at the quantized vs shipping precision:
    # rows bounded so the probe stays a footnote next to the training loop
    hist_rows = min(n_rows, 262144)
    hist_bins = bst._driver.learner.num_bins
    bins_np = np.asarray(ds._inner.bins[:hist_rows])
    hist_int8, hist_int8_min = spread(
        hist_rows_per_sec(bins_np, hist_bins, "int8"))
    hist_hilo, hist_hilo_min = spread(
        hist_rows_per_sec(bins_np, hist_bins, "hilo"))
    # ISSUE 18: fused frontier megakernel throughput + the autotune
    # profile round-trip cost, as first-class bench metrics with
    # bench_diff rows
    fused_frontier, fused_frontier_min = spread(
        fused_frontier_rows_per_sec_probe(bins_np, hist_bins))
    autotune_ms = autotune_resolve_ms_probe(hist_bins)
    n_programs = LEDGER.n_programs()
    ledger_sites = {a["site"]: a["programs"] for a in LEDGER.report()}

    # ISSUE 12: resource accounting — peak device bytes (None on CPU:
    # the backend reports no memory_stats, and a null beats a fiction),
    # phase watermarks, and the per-program static cost table (flops /
    # bytes-accessed everywhere; HBM byte fields where the backend
    # reports, i.e. auto-skipped on CPU)
    res = resources.bench_resource_metrics(
        LEDGER, train_peak=train_peak_hbm_bytes)

    # regression note: compile_s / n_programs against the newest
    # committed BENCH_r*.json (same-shape comparisons only make sense
    # between degraded rounds or between TPU rounds; the note carries the
    # prior platform so readers can judge)
    prior_name, prior = prior_bench_record()
    compile_note = None
    if prior is not None:
        note = {"vs": prior_name,
                "prior_compile_s": prior.get("compile_s"),
                "prior_platform": prior.get("platform")}
        if prior.get("n_programs") is not None:
            note["prior_n_programs"] = prior.get("n_programs")
        try:
            note["compile_s_ratio"] = round(
                compile_s / float(prior["compile_s"]), 3)
        except (KeyError, TypeError, ZeroDivisionError):
            pass
        compile_note = note

    # sanity: the model must actually learn (pred captured above, at
    # exactly bench_iters + warmup iterations)
    from lightgbm_tpu.models.metrics import AUCMetric
    from lightgbm_tpu.config import Config
    m = AUCMetric(Config())

    class _MD:
        label = y[:n_eval].astype(np.float32)
        weight = None
    m.init(_MD, n_eval)
    eps = 1e-9
    margin = (np.log(np.clip(pred, eps, 1 - eps))
              - np.log(np.clip(1 - pred, eps, 1 - eps)))
    auc = m.eval(margin[None, :], None)

    out = {
        "metric": "higgs1m_boosting_iters_per_sec",
        "value": round(iters_per_sec, 3),
        "unit": f"iters/s ({n_rows} rows, 28 feats, {num_leaves} leaves, "
                f"{max_bin} bins)",
        # off-shape runs: a ratio against the full-size baseline would be
        # fiction, so report 0.0 unless the problem matches the baseline's
        "vs_baseline": (round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3)
                        if comparable else 0.0),
        "train_auc": round(float(auc), 4),
        # every timed metric: median of >=3 repeats, worst repeat beside
        # it (the _min twin) so each record carries its own variance
        "timing_repeats": 3,
        "iters_per_sec_min": round(iters_per_sec_min, 3),
        "predict_rows_per_sec": round(predict_rows_per_sec, 0),
        "predict_rows_per_sec_min": round(predict_rows_per_sec_min, 0),
        "serve_rows_per_sec": round(serve_rows_per_sec, 0),
        "serve_rows_per_sec_min": round(serve_rows_per_sec_min, 0),
        "serve_p99_ms": round(serve_p99_ms, 1),
        "serve_goodput_rows_per_sec": round(serve_goodput_rows_per_sec, 0),
        "serve_shed_pct": round(serve_shed_pct, 1),
        "drift_overhead_pct": round(drift_overhead_pct, 1),
        "eval_ms_per_iter": round(eval_ms_per_iter, 1),
        "checkpoint_overhead_pct": round(checkpoint_overhead_pct, 2),
        "resume_s": round(resume_s, 2),
        "resume_elastic_s": round(resume_elastic_s, 2),
        "collective_timeout_recovery_s": round(
            collective_timeout_recovery_s, 2),
        # ISSUE 15: injected mid-train OOM -> settled completion wall,
        # and budget-vs-peak headroom (null on CPU, no budget resolves)
        "oom_recovery_s": round(oom_recovery_s, 2),
        "hbm_budget_headroom_bytes": hbm_budget_headroom_bytes,
        "hist_int8_rows_per_sec": round(hist_int8, 0),
        "hist_int8_rows_per_sec_min": round(hist_int8_min, 0),
        "hist_hilo_rows_per_sec": round(hist_hilo, 0),
        "hist_hilo_rows_per_sec_min": round(hist_hilo_min, 0),
        # ISSUE 18: per-iteration grow wall (the fused-frontier headline
        # in ms terms), the grow megakernel's probe throughput, and the
        # steady-state autotune profile load+resolve cost
        "grow_iter_ms": round(1000.0 * train_s / max(bench_iters, 1), 2),
        "fused_frontier_rows_per_sec": round(fused_frontier, 0),
        "fused_frontier_rows_per_sec_min": round(fused_frontier_min, 0),
        "autotune_resolve_ms": round(autotune_ms, 2),
        "ingest_rows_per_sec": round(ingest_rows_per_sec, 0),
        # ISSUE 16: out-of-core streaming — throughput at 4x the base
        # row count, overlap achieved, and the full scaling curve
        "stream_rows_per_sec": stream_rows_per_sec,
        "stream_overlap_pct": round(stream_overlap_pct, 1),
        "stream_scaling_rows_per_sec": stream_scaling,
        "bench_iters": bench_iters,
        "data_gen_s": round(data_s, 1),
        "binning_s": round(bin_s, 1),
        "sketch_s": round(phases.get("sketch", 0.0), 2),
        "bin_s": round(phases.get("binning", 0.0), 2),
        "layout_s": round(phases.get("layout", 0.0), 2),
        "compile_s": round(compile_s, 1),
        # compiled XLA programs recorded by the ledgered jit sites: the
        # train+warmup lifecycle count, then the whole round (predict +
        # serve shapes included)
        "n_programs_train": n_programs_train,
        "n_programs": n_programs,
        "ledger_sites": ledger_sites,
        # ISSUE 12: device memory/cost accounting.  Fields derived from
        # device memory_stats (train/phase peaks, program memory bytes)
        # are explicitly null on CPU — "not measurable here", not
        # "missing"; serve_model_hbm_bytes (packed-table bytes on
        # whatever backend holds them — host RAM on CPU) and the cost
        # table's flops/bytes_accessed are real numbers everywhere.
        # Train peak snapshotted right after the train segments, before
        # predict/serve could raise the process high-water mark
        "train_peak_hbm_bytes": res["train_peak_hbm_bytes"],
        "phase_peak_hbm_bytes": res["phase_peak_hbm_bytes"],
        "serve_model_hbm_bytes": serve_model_hbm_bytes,
        "program_costs": res["program_costs"],
        "platform": jax.devices()[0].platform,
        # ISSUE 10 satellite: the backend/degraded marker lives IN the
        # record (it used to go only to stderr, so rounds 3-5's silent
        # CPU fallback could not be audited post hoc from the JSON)
        "backend": jax.devices()[0].platform,
        "degraded": bool(degraded),
    }
    if compile_note is not None:
        out["compile_vs_prior"] = compile_note
    if params["tpu_shape_buckets"]:
        out["tpu_shape_buckets"] = params["tpu_shape_buckets"]
    if cache_dir:
        out["compile_cache"] = cache_state  # cold|warm; compile_s pairs

    if degraded:
        out["degraded_reason"] = (
            "tpu backend probe failed; reduced-size run on cpu fallback "
            "— value NOT comparable to baseline")
    if obs.tracing_on():
        obs.write_chrome_trace()
        obs.flush()
    print(json.dumps(out))


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from lightgbm_tpu.utils.backend import (backend_health,
                                            pin_cpu_backend,
                                            probe_default_backend)

    # the round-5 postmortem: the old bounded re-probe window (up to 420s
    # of 30s sleeps) burned the outer harness deadline on genuinely-dead
    # tunnels.  ONE short retry only — a tunnel that is down twice in
    # quick succession is down for the round — and the degraded marker
    # goes to stderr IMMEDIATELY so log readers see the downgrade at the
    # moment it is decided, not after the whole reduced run.
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", 90))
    retry_sleep_s = float(os.environ.get("BENCH_PROBE_RETRY_SLEEP", 5))
    platform = probe_default_backend(timeout_s=timeout_s, retries=0)
    # only 'probe' (tunneled factory registered, init may hang) is worth
    # re-probing: 'broken' fails deterministically and 'ok' means no
    # tunnel exists, so a retry there just burns the outer deadline
    if platform in (None, "cpu") and backend_health() == "probe":
        print("# backend probe failed with a tunneled backend registered; "
              f"one retry in {retry_sleep_s:.0f}s", file=sys.stderr)
        time.sleep(retry_sleep_s)
        platform = probe_default_backend(timeout_s=timeout_s, retries=0)
    degraded = platform is None or platform == "cpu"
    if degraded:
        print("# degraded: tpu backend probe failed; reduced-size run on "
              "cpu fallback", file=sys.stderr)
        pin_cpu_backend()
        n_rows = int(os.environ.get("BENCH_ROWS", 50_000))
        num_leaves = int(os.environ.get("BENCH_LEAVES", 63))
        max_bin = int(os.environ.get("BENCH_BINS", 63))
        bench_iters = int(os.environ.get("BENCH_ITERS", 5))
    else:
        n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
        num_leaves = int(os.environ.get("BENCH_LEAVES", 255))
        max_bin = int(os.environ.get("BENCH_BINS", 255))
        bench_iters = int(os.environ.get("BENCH_ITERS", 25))
    # a vs_baseline ratio is only honest on the baseline's own problem
    # shape (Higgs-1M, 255 leaves, 255 bins), whatever the platform
    comparable = (n_rows >= 1_000_000 and num_leaves == 255
                  and max_bin == 255)
    try:
        run(n_rows, num_leaves, max_bin, bench_iters, degraded, comparable)
    except Exception as exc:  # emit a parseable failure record, not a trace
        print(json.dumps({
            "metric": "higgs1m_boosting_iters_per_sec",
            "value": 0.0,
            "unit": "iters/s",
            "vs_baseline": 0.0,
            # even a crashed round records which backend it was on
            "backend": platform or "none",
            "degraded": bool(degraded),
            "error": f"{type(exc).__name__}: {exc}",
            "trace_tail": traceback.format_exc().strip().splitlines()[-3:],
        }))
        sys.exit(1)


if __name__ == "__main__":
    main()
