"""Benchmark: Higgs-shaped GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark (BASELINE.md: Higgs, 500 trees,
255 leaves, lr=0.1 — 238.5 s on 2x E5-2670v3, i.e. 2.096 boosting iters/s).
The real Higgs dataset cannot be fetched here (no egress), so the data is a
seeded synthetic with Higgs dimensions (1M rows x 28 dense features) and a
nonlinear separable structure; histogram/split work depends only on shape,
bins, and leaf count, so iters/sec is comparable.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_FEATURES = 28
NUM_LEAVES = 255
MAX_BIN = 255
WARMUP_ITERS = 3
BENCH_ITERS = int(os.environ.get("BENCH_ITERS", 25))
BASELINE_ITERS_PER_SEC = 500.0 / 238.5  # reference Higgs CPU (BASELINE.md)


def make_data(n, f, seed=42):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=(f,))
    logits = (X[:, :8] ** 2 - 1.0).sum(axis=1) * 0.3 + X @ w * 0.5
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.booster import Booster

    t_data = time.time()
    X, y = make_data(N_ROWS, N_FEATURES)
    data_s = time.time() - t_data

    t_bin = time.time()
    ds = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    ds.construct()
    bin_s = time.time() - t_bin
    X_eval = X[:50000].copy()
    del X

    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "max_bin": MAX_BIN}
    bst = Booster(params=params, train_set=ds)
    t_compile = time.time()
    for _ in range(WARMUP_ITERS):
        bst.update()
    jax.block_until_ready(bst._driver.train_scores.scores)
    compile_s = time.time() - t_compile

    t0 = time.time()
    for _ in range(BENCH_ITERS):
        bst.update()
    jax.block_until_ready(bst._driver.train_scores.scores)
    train_s = time.time() - t0
    iters_per_sec = BENCH_ITERS / train_s

    # sanity: the model must actually learn
    t_eval = time.time()
    sample = slice(0, 50000)
    pred = bst.predict(X_eval)
    from lightgbm_tpu.models.metrics import AUCMetric
    from lightgbm_tpu.config import Config
    m = AUCMetric(Config())

    class _MD:
        label = y[sample].astype(np.float32)
        weight = None
    m.init(_MD, 50000)
    auc = m.eval(np.log(np.clip(pred, 1e-9, 1 - 1e-9))[None, :]
                 - np.log(np.clip(1 - pred, 1e-9, 1 - 1e-9))[None, :], None)
    eval_s = time.time() - t_eval

    print(json.dumps({
        "metric": "higgs1m_boosting_iters_per_sec",
        "value": round(iters_per_sec, 3),
        "unit": "iters/s (1M rows, 28 feats, 255 leaves, 255 bins)",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
        "train_auc_50k": round(float(auc), 4),
        "bench_iters": BENCH_ITERS,
        "data_gen_s": round(data_s, 1),
        "binning_s": round(bin_s, 1),
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
